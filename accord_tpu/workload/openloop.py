"""Open-loop drivers: intended-start scheduling over the sim and TCP hosts.

Both runners share the measurement discipline that closed-loop bench lanes
cannot provide:

  * arrivals follow a pre-computed schedule (arrival.py) — completions
    never gate submissions, so a stalled coordinator backs work up instead
    of silently pausing the load;
  * every op's latency is measured from its INTENDED start (the schedule
    time), charging omitted time to the tail; the same acked ops measured
    from actual submit give the closed-loop comparison — the delta IS the
    coordinated omission;
  * acked ops join the PR-2 trace spans (obs/spans.phase_firsts) for
    per-phase attribution, plus a synthetic "admission" phase
    (coordination begin - intended start: client scheduling, any stall
    ahead of the coordinator, and pipeline queueing).

The sim runner (`run_open_loop_sim`) is fully deterministic — virtual-time
arrivals on the shared PendingQueue — and supports stall injection: during
[stall_at_us, stall_at_us+stall_us) submissions are HELD AT THE
COORDINATOR'S DOOR and released when the stall ends, the externally
observable behavior of a wedged event loop (a client cannot observe which
internal stage stalled, only that its op sat).  The TCP runner drives the
real multi-process cluster on the wall clock; per-phase data rides back on
submit replies (`want_phases`).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from accord_tpu.utils.random_source import RandomSource
from accord_tpu.workload.arrival import make_offsets_us
from accord_tpu.workload.profiles import build_txn, make_profile

# bounded exact-sample buffers: enough for sample-exact p99.9 at every
# realistic lane size, bounded against a runaway caller
MAX_SAMPLES = 1 << 17


class OpRecord:
    """One op's ledger row: intended vs actual submit vs end."""

    __slots__ = ("idx", "intended_us", "submit_us", "end_us", "outcome",
                 "phase_firsts")

    def __init__(self, idx: int, intended_us: int):
        self.idx = idx
        self.intended_us = intended_us
        self.submit_us: Optional[int] = None
        self.end_us: Optional[int] = None
        self.outcome: Optional[str] = None  # ack | shed | fail | None
        self.phase_firsts: Optional[list] = None  # [(phase, at_us)]


class OpenLoopResult:
    """Ledger + SLO report of one open-loop run."""

    def __init__(self, records: List[OpRecord], report: dict,
                 summary: Optional[dict], schedule: dict):
        self.records = records
        self.report = report
        self.summary = summary
        self.schedule = schedule

    @property
    def counts(self) -> Dict[str, int]:
        return self.report["counts"]


def _collect(records: List[OpRecord], offered_per_s: float,
             schedule: dict, summary: Optional[dict],
             t0_us: int) -> dict:
    """Fold the ledger into the SLO report (obs/report.slo_report)."""
    from accord_tpu.obs.report import slo_report
    from accord_tpu.obs.spans import phase_deltas

    open_lat: List[int] = []
    closed_lat: List[int] = []
    phases: Dict[str, List[int]] = {}
    counts = {"acked": 0, "shed": 0, "failed": 0, "pending": 0}
    last_end = t0_us
    for rec in records:
        if rec.outcome == "ack":
            counts["acked"] += 1
            last_end = max(last_end, rec.end_us)
            if len(open_lat) < MAX_SAMPLES:
                open_lat.append(max(0, rec.end_us - rec.intended_us))
                closed_lat.append(max(0, rec.end_us - rec.submit_us))
            firsts = rec.phase_firsts or []
            if firsts:
                # admission: intended start -> coordination begin (client
                # scheduling + stall + pipeline queue), then the span's
                # own milestone deltas
                begin_at = firsts[0][1]
                phases.setdefault("admission", []).append(
                    max(0, begin_at - rec.intended_us))
                for ph, dur in phase_deltas(firsts):
                    if ph != "end":
                        phases.setdefault(ph, []).append(dur)
        elif rec.outcome == "shed":
            counts["shed"] += 1
        elif rec.outcome == "fail":
            counts["failed"] += 1
        else:
            counts["pending"] += 1
    duration_s = max(1e-9, (last_end - t0_us) / 1e6)
    return slo_report(open_lat, closed_lat, phases, counts, offered_per_s,
                      duration_s, schedule=schedule, summary=summary)


# ------------------------------------------------------------- sim host ----

def run_open_loop_sim(profile: str = "zipfian", ops: int = 400,
                      rate_per_s: float = 400.0, schedule: str = "poisson",
                      seed: int = 0, nodes: int = 3, keys: int = 48,
                      n_shards: int = 4, pipeline: bool = True,
                      stall_at_us: Optional[int] = None, stall_us: int = 0,
                      store_factory: Optional[Callable] = None,
                      profile_kwargs: Optional[dict] = None,
                      keep_cluster: bool = False) -> OpenLoopResult:
    """Deterministic open-loop run through the pipeline host in the sim:
    arrivals at virtual-time offsets, latencies in virtual microseconds.

    stall_at_us/stall_us: hold every submission landing inside the window
    until it closes (a stalled coordinator as the client observes one).
    Open-loop latency charges the hold (intended start predates it);
    closed-loop latency of the SAME run does not — the coordinated-
    omission demonstration (tests/test_workload.py)."""
    from accord_tpu.sim.cluster import SimCluster

    rng = RandomSource(seed)
    cluster = SimCluster(n_nodes=nodes, seed=rng.next_long(),
                         n_shards=n_shards, pipeline=pipeline,
                         store_factory=store_factory)
    cluster.start_durability_scheduling(shard_cycle_s=10.0)
    prof = make_profile(profile, keys=keys, seed=rng.next_long(),
                        **(profile_kwargs or {}))
    offsets = make_offsets_us(schedule, rate_per_s, ops,
                              seed=rng.next_long())
    origin_rng = rng.fork()
    t0_us = cluster.queue.clock.now_us
    records = [OpRecord(i, t0_us + off) for i, off in enumerate(offsets)]
    ops_list = [prof.next_op() for _ in range(ops)]
    settled = [0]
    stall_end_us = (t0_us + stall_at_us + stall_us
                    if stall_at_us is not None and stall_us > 0 else None)
    stall_begin_us = (t0_us + stall_at_us
                      if stall_end_us is not None else None)

    def submit(i: int) -> None:
        now = cluster.queue.clock.now_us
        if stall_end_us is not None and stall_begin_us <= now < stall_end_us:
            # coordinator wedged: the op sits until the stall clears
            cluster.queue.add(stall_end_us - now, lambda: submit(i))
            return
        rec = records[i]
        rec.submit_us = now
        origin = origin_rng.pick(cluster.live_node_ids())
        txn = build_txn(ops_list[i])

        def done(value, failure):
            from accord_tpu.pipeline.backpressure import Rejected
            rec.end_us = cluster.queue.clock.now_us
            settled[0] += 1
            if isinstance(failure, Rejected):
                rec.outcome = "shed"
            elif failure is not None:
                rec.outcome = "fail"
            elif value is not None:
                rec.outcome = "ack"
                from accord_tpu.obs.spans import phase_firsts, trace_key
                span = cluster.nodes[origin].obs.spans.get(
                    trace_key(value.txn_id))
                rec.phase_firsts = phase_firsts(span)
            else:
                rec.outcome = "fail"

        cluster.pipeline_submit(origin, txn).add_callback(done)

    for i, off in enumerate(offsets):
        cluster.queue.add(off, (lambda j: (lambda: submit(j)))(i))
    cluster.process_until(lambda: settled[0] >= ops, max_items=50_000_000)

    summary = cluster.metrics_snapshot()["summary"]
    sched = {"kind": schedule, "rate_per_s": rate_per_s, "ops": ops,
             "seed": seed, "host": "sim-pipeline" if pipeline else "sim"}
    if stall_end_us is not None:
        sched["stall_at_us"] = stall_at_us
        sched["stall_us"] = stall_us
    result = OpenLoopResult(records,
                            _collect(records, rate_per_s, sched, summary,
                                     t0_us),
                            summary, sched)
    if keep_cluster:
        result.cluster = cluster
    return result


# ------------------------------------------------------------- tcp host ----

def run_open_loop_tcp(profile: str = "zipfian", ops: int = 300,
                      rate_per_s: float = 100.0, schedule: str = "poisson",
                      seed: int = 7, nodes: int = 3, keys: int = 64,
                      n_shards: int = 4, want_phases: bool = True,
                      profile_kwargs: Optional[dict] = None,
                      settle_timeout_s: float = 60.0) -> OpenLoopResult:
    """Open-loop run over the REAL multi-process TCP cluster (wall clock).
    ACCORD_PIPELINE et al. are read by the node processes from the ambient
    environment — the caller chooses the host configuration.  Range ops are
    sim-only (the submit frame carries no range encoding)."""
    from accord_tpu.host.tcp import TcpClusterClient

    rng = RandomSource(seed)
    prof = make_profile(profile, keys=keys, seed=rng.next_long(),
                        **(profile_kwargs or {}))
    offsets = make_offsets_us(schedule, rate_per_s, ops,
                              seed=rng.next_long())
    ops_list = [prof.next_op() for _ in range(ops)]
    assert all(op.ranges is None for op in ops_list), \
        "range ops are sim-only (no wire encoding on the submit frame)"
    origin_rng = rng.fork()
    origins = [1 + origin_rng.next_int(nodes) for _ in range(ops)]

    client = TcpClusterClient(n_nodes=nodes, n_shards=n_shards)
    summary = None
    try:
        t0_us = int(time.time() * 1e6)
        records = [OpRecord(i, t0_us + off) for i, off in enumerate(offsets)]

        def handle(frame) -> bool:
            body = frame.get("body", {})
            if body.get("type") != "submit_reply":
                return False
            rec = records[body["req"]]
            rec.end_us = int(time.time() * 1e6)
            if body.get("ok"):
                rec.outcome = "ack"
                if body.get("phases"):
                    rec.phase_firsts = [(ph, at) for ph, at
                                        in body["phases"]]
            elif body.get("shed"):
                rec.outcome = "shed"
            else:
                rec.outcome = "fail"
            return True

        sent = pending = 0
        while sent < ops:
            due_us = records[sent].intended_us
            now_us = int(time.time() * 1e6)
            if now_us < due_us:
                frame = client.recv(min(0.05, (due_us - now_us) / 1e6))
                if frame is not None and handle(frame):
                    pending -= 1
                continue
            op = ops_list[sent]
            records[sent].submit_us = int(time.time() * 1e6)
            client.submit(origins[sent], op.reads, op.appends, sent,
                          ephemeral=op.ephemeral, want_phases=want_phases)
            sent += 1
            pending += 1
        deadline = time.monotonic() + settle_timeout_s
        while pending > 0 and time.monotonic() < deadline:
            frame = client.recv(1.0)
            if frame is not None and handle(frame):
                pending -= 1

        # obs snapshots AFTER the channel quiesces (fetch_metrics drops
        # stray frames); merged summary feeds fast_path_ratio into the row
        from accord_tpu.obs.report import merge_node_snapshots
        snaps = [client.fetch_metrics(i) for i in range(1, nodes + 1)]
        merged = merge_node_snapshots([s for s in snaps if s])
        summary = merged["summary"] if merged["nodes"] else None
    finally:
        client.close()

    sched = {"kind": schedule, "rate_per_s": rate_per_s, "ops": ops,
             "seed": seed, "host": "tcp"}
    return OpenLoopResult(records,
                          _collect(records, rate_per_s, sched, summary,
                                   t0_us),
                          summary, sched)
