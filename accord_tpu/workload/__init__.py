"""Open-loop SLO workload harness (ISSUE 6).

Closed-loop clients (a fixed in-flight window, submit-on-ack) suffer
coordinated omission: when a coordinator stalls, the client stops
submitting, so the stall never appears in the recorded latencies — the
measurement understates tail latency exactly when the slow path, recovery,
or an fsync stall fires.  This package generates load OPEN-LOOP instead:
arrival times are fixed by a deterministic-seeded schedule (`arrival.py`),
independent of completions, and every latency is measured from the op's
INTENDED start — omitted time is charged, not hidden.

`profiles.py` names the workload shapes (zipfian hot-key skew, range-stab
mix, TPC-C-style neworder, ephemeral-read-heavy); `openloop.py` drives them
end-to-end through the pipeline host — the deterministic sim cluster
(virtual time) or the multi-process TCP cluster (wall time) — and joins the
intended-start ledger against the PR-2 trace spans for per-phase latency
attribution.  The SLO report itself (exact-sample p50/p99/p99.9 overall,
per phase, open- vs closed-loop) is built by `obs/report.slo_report`.
"""

from accord_tpu.workload.arrival import make_offsets_us
from accord_tpu.workload.openloop import (run_open_loop_sim,
                                          run_open_loop_tcp,
                                          run_reshard_tcp)
from accord_tpu.workload.profiles import PROFILES, build_txn, make_profile

__all__ = ["PROFILES", "build_txn", "make_profile", "make_offsets_us",
           "run_open_loop_sim", "run_open_loop_tcp", "run_reshard_tcp"]
