"""Arrival-rate schedules for the open-loop generator.

An open-loop client decides WHEN each op starts before the run begins; the
cluster's behavior can delay completions but never arrivals.  Schedules are
deterministic from (kind, rate, n, seed) so a lane is reproducible and a
regression bisectable — the Poisson schedule draws its exponential
inter-arrival gaps from the same `RandomSource` the sim uses everywhere.

All times are integer microsecond OFFSETS from the run's t0 (virtual or
wall); the runner adds its own epoch.
"""

from __future__ import annotations

import math
from typing import List

from accord_tpu.utils.random_source import RandomSource

SCHEDULE_KINDS = ("poisson", "paced")


def paced_offsets_us(rate_per_s: float, n: int) -> List[int]:
    """Uniformly paced arrivals: op i at i/rate.  The harshest schedule for
    a batching tier (no natural bursts to coalesce)."""
    assert rate_per_s > 0 and n >= 0
    gap_us = 1e6 / rate_per_s
    return [int(i * gap_us) for i in range(n)]


def poisson_offsets_us(rate_per_s: float, n: int, seed: int) -> List[int]:
    """Poisson arrivals at `rate_per_s`: i.i.d. exponential gaps, the
    classic open-system model (bursts and lulls at every scale)."""
    assert rate_per_s > 0 and n >= 0
    rng = RandomSource(seed)
    at = 0.0
    out = []
    for _ in range(n):
        # inverse-CDF exponential; guard the u=0 edge of next_float
        u = rng.next_float()
        at += -math.log(1.0 - u if u < 1.0 else 0.5) * (1e6 / rate_per_s)
        out.append(int(at))
    return out


def make_offsets_us(kind: str, rate_per_s: float, n: int,
                    seed: int = 0) -> List[int]:
    if kind == "paced":
        return paced_offsets_us(rate_per_s, n)
    if kind == "poisson":
        return poisson_offsets_us(rate_per_s, n, seed)
    raise ValueError(f"unknown schedule kind {kind!r}; "
                     f"one of {SCHEDULE_KINDS}")
