"""TopologySorter: contact-ordering policy for coordination rounds.

Reference: accord/api/TopologySorter.java (comparator SPI; least preferable
first) + accord/impl/SizeOfIntersectionSorter.java — prefer replicas that
appear in MORE shards of the selection: one message to such a node advances
more shard quorums, so reads and fan-outs favour them.

Ours exposes `sort(nodes, topologies)` returning most-preferable first (the
order consumers like ReadTracker.initial_contacts take directly), with node
id as the deterministic tie-break.
"""

from __future__ import annotations

from typing import List, Sequence


class TopologySorter:
    def sort(self, nodes: Sequence[int], topologies) -> List[int]:
        raise NotImplementedError


class SizeOfIntersectionSorter(TopologySorter):
    """Order by how many shards across the epoch window each node replicates
    (SizeOfIntersectionSorter.compare counts shard memberships the same
    way), descending; ties by node id."""

    def sort(self, nodes: Sequence[int], topologies) -> List[int]:
        def intersections(node: int) -> int:
            return sum(1 for topology in topologies
                       for shard in topology.shards
                       if node in shard.nodes)

        return sorted(nodes, key=lambda n: (-intersections(n), n))


SIZE_OF_INTERSECTION = SizeOfIntersectionSorter()
