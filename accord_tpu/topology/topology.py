"""Topology: one epoch's shard layout (reference: accord/topology/Topology.java:59-540)."""

from __future__ import annotations

import bisect
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from accord_tpu.primitives.keys import Range, Ranges, Route, RoutingKey, _SortedKeyList
from accord_tpu.topology.shard import Shard
from accord_tpu.utils import invariants


class Topology:
    __slots__ = ("epoch", "shards", "ranges", "_starts", "_node_shards",
                 "_node_ranges", "_selection_memo")

    EMPTY: "Topology"

    def __init__(self, epoch: int, shards: Sequence[Shard]):
        self.epoch = epoch
        self.shards: Tuple[Shard, ...] = tuple(
            sorted(shards, key=lambda s: (s.range.start, s.range.end)))
        # shard ranges must not overlap
        for a, b in zip(self.shards, self.shards[1:]):
            invariants.check_argument(a.range.end <= b.range.start,
                                      "shard ranges overlap")
        self.ranges = Ranges([s.range for s in self.shards])
        self._starts = [s.range.start for s in self.shards]
        node_shards: Dict[int, List[int]] = {}
        for i, s in enumerate(self.shards):
            for n in s.nodes:
                node_shards.setdefault(n, []).append(i)
        self._node_shards = {n: tuple(ix) for n, ix in node_shards.items()}
        # per-node Ranges memo: topologies are immutable and
        # ranges_for_node runs per destination per message send
        # (TxnRequest.compute_scope)
        self._node_ranges: Dict[int, Ranges] = {}
        # for_selection memo keyed by participant-object identity: a txn's
        # coordination rounds re-select with the SAME route participants
        # object 3-4 times per epoch window.  Values hold a strong ref to
        # the key object, so a live entry's id cannot be reused; bounded by
        # wholesale clear.
        self._selection_memo: Dict[int, Tuple] = {}

    # -- basic accessors --
    @property
    def size(self) -> int:
        return len(self.shards)

    def nodes(self) -> FrozenSet[int]:
        return frozenset(self._node_shards.keys())

    def contains_node(self, node: int) -> bool:
        return node in self._node_shards

    def shards_for_node(self, node: int) -> List[Shard]:
        return [self.shards[i] for i in self._node_shards.get(node, ())]

    def ranges_for_node(self, node: int) -> Ranges:
        r = self._node_ranges.get(node)
        if r is None:
            r = self._node_ranges[node] = Ranges(
                [self.shards[i].range
                 for i in self._node_shards.get(node, ())])
        return r

    def shard_for_key(self, key: RoutingKey) -> Optional[Shard]:
        i = bisect.bisect_right(self._starts, key.token) - 1
        if i >= 0 and self.shards[i].contains(key):
            return self.shards[i]
        return None

    def shard_for_token(self, token: int) -> Optional[Shard]:
        return self.shard_for_key(RoutingKey(token))

    # -- selection over routables (Topology.forSelection / mapReduceOn) --
    def shards_for(self, select) -> List[Shard]:
        """Shards intersecting a Keys/RoutingKeys/Ranges/Route selection,
        in range order."""
        if isinstance(select, Route):
            select = select.participants()
        out: List[Shard] = []
        if isinstance(select, _SortedKeyList):
            ki = 0
            for s in self.shards:
                while ki < len(select) and select[ki].token < s.range.start:
                    ki += 1
                if ki < len(select) and s.range.contains(select[ki]):
                    out.append(s)
            return out
        if isinstance(select, Ranges):
            for s in self.shards:
                if select.intersects(s.range):
                    out.append(s)
            return out
        raise TypeError(type(select))

    def for_selection(self, select) -> "Topology":
        """Sub-topology of shards intersecting the selection (forSelection)."""
        memo = self._selection_memo
        hit = memo.get(id(select))
        if hit is not None and hit[0] is select:
            return hit[1]
        sub = Topology(self.epoch, self.shards_for(select))
        if len(memo) > 256:
            memo.clear()
        memo[id(select)] = (select, sub)
        return sub

    def for_node(self, node: int) -> "Topology":
        return Topology(self.epoch, self.shards_for_node(node))

    def map_reduce_on(self, select, map_fn: Callable[[Shard], object],
                      reduce_fn: Callable[[object, object], object],
                      initial=None):
        acc = initial
        for s in self.shards_for(select):
            v = map_fn(s)
            acc = v if acc is None else reduce_fn(acc, v)
        return acc

    def foldl(self, select, fn: Callable, acc):
        for s in self.shards_for(select):
            acc = fn(acc, s)
        return acc

    def for_each(self, fn: Callable[[Shard], None]) -> None:
        for s in self.shards:
            fn(s)

    def nodes_for(self, select) -> FrozenSet[int]:
        out: Set[int] = set()
        for s in self.shards_for(select):
            out.update(s.nodes)
        return frozenset(out)

    def __eq__(self, other):
        return (isinstance(other, Topology) and self.epoch == other.epoch
                and self.shards == other.shards)

    def __hash__(self):
        return hash((self.epoch, self.shards))

    def __repr__(self):
        return f"Topology(e{self.epoch}, {len(self.shards)} shards)"


Topology.EMPTY = Topology(0, ())
