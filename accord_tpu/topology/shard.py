"""Shard: one replicated range with its quorum arithmetic.

Reference: accord/topology/Shard.java:38-96. The fast-path electorate is the
subset of replicas whose votes count toward the single-round-trip fast path;
quorum sizes follow the Accord paper's intersection requirements:
  maxFailures          = (rf - 1) // 2
  slowPathQuorumSize   = rf - maxFailures                (simple majority)
  fastPathQuorumSize   = (f + e) // 2 + 1, requiring e >= rf - f
  recoveryFastPathSize = (maxFailures + 1) // 2
"""

from __future__ import annotations

from typing import FrozenSet, Sequence, Tuple

from accord_tpu.primitives.keys import Range, RoutingKey
from accord_tpu.utils import invariants


def max_tolerated_failures(replicas: int) -> int:
    return (replicas - 1) // 2


def slow_path_quorum_size(replicas: int) -> int:
    return replicas - max_tolerated_failures(replicas)


def fast_path_quorum_size(replicas: int, electorate: int, f: int) -> int:
    invariants.check_argument(electorate >= replicas - f,
                              "electorate must include at least rf - f replicas")
    return (f + electorate) // 2 + 1


class Shard:
    __slots__ = ("range", "nodes", "sorted_nodes", "fast_path_electorate",
                 "joining", "max_failures", "recovery_fast_path_size",
                 "fast_path_quorum_size", "slow_path_quorum_size")

    def __init__(self, range_: Range, nodes: Sequence[int],
                 fast_path_electorate: FrozenSet[int] = None,
                 joining: FrozenSet[int] = None):
        self.range = range_
        self.nodes: Tuple[int, ...] = tuple(nodes)
        self.sorted_nodes: Tuple[int, ...] = tuple(sorted(nodes))
        electorate = (frozenset(fast_path_electorate)
                      if fast_path_electorate is not None else frozenset(nodes))
        self.fast_path_electorate = electorate
        self.joining = frozenset(joining) if joining else frozenset()
        invariants.check_argument(self.joining <= set(nodes),
                                  "joining nodes must also be present in nodes")
        self.max_failures = max_tolerated_failures(len(self.nodes))
        self.recovery_fast_path_size = (self.max_failures + 1) // 2
        self.slow_path_quorum_size = slow_path_quorum_size(len(self.nodes))
        self.fast_path_quorum_size = fast_path_quorum_size(
            len(self.nodes), len(electorate), self.max_failures)

    @property
    def rf(self) -> int:
        return len(self.nodes)

    def contains(self, key: RoutingKey) -> bool:
        return self.range.contains(key)

    def contains_node(self, node: int) -> bool:
        return node in self.nodes

    def is_in_electorate(self, node: int) -> bool:
        return node in self.fast_path_electorate

    def rejects_fast_path(self, reject_count: int) -> bool:
        """Have enough electorate votes been lost that the fast path cannot
        reach quorum? (Shard.java:84-87)"""
        return reject_count > len(self.fast_path_electorate) - self.fast_path_quorum_size

    def __eq__(self, other):
        return (isinstance(other, Shard) and self.range == other.range
                and self.nodes == other.nodes
                and self.fast_path_electorate == other.fast_path_electorate
                and self.joining == other.joining)

    def __hash__(self):
        return hash((self.range, self.nodes))

    def __repr__(self):
        return (f"Shard({self.range!r}, nodes={list(self.nodes)}, "
                f"electorate={sorted(self.fast_path_electorate)})")
