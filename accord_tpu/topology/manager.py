"""TopologyManager: the per-node epoch ledger and sync tracker.

Reference: accord/topology/TopologyManager.java:70-671. Tracks every known
epoch's topology, which peers have completed their inter-epoch sync (a
per-shard quorum of sync acknowledgements unlocks the epoch for precise
coordination), pending futures for unknown epochs, and the epoch-window
selection used by coordinators (`with_unsynced_epochs` / `precise_epochs`).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from accord_tpu.primitives.keys import Ranges, Route
from accord_tpu.topology.topologies import Topologies
from accord_tpu.topology.topology import Topology
from accord_tpu.utils import invariants
from accord_tpu.utils.async_chains import AsyncResult, success


def _covered_by(select, ranges: Ranges) -> bool:
    """Is the selection (Route / Ranges / sorted key list) fully inside
    `ranges`?  Used to decide per-range sync unlock."""
    if isinstance(select, Route):
        select = select.participants()
    if isinstance(select, Ranges):
        return ranges.contains_all_ranges(select)
    return ranges.contains_all_keys(select)


class EpochState:
    __slots__ = ("global_topology", "synced_nodes", "sync_complete",
                 "synced_ranges", "closed", "redundant")

    def __init__(self, global_topology: Topology):
        self.global_topology = global_topology
        self.synced_nodes: Set[int] = set()
        self.sync_complete = False
        self.synced_ranges: Ranges = Ranges.EMPTY  # per-shard quorum-synced
        self.closed: Ranges = Ranges.EMPTY      # ranges no longer coordinated here
        self.redundant: Ranges = Ranges.EMPTY   # ranges fully superseded

    def recompute_sync(self) -> bool:
        """Accumulate quorum-synced shard ranges; sync-complete once every
        shard has a (slow-path) quorum of synced replicas.

        Per-range granularity mirrors the reference's curSyncComplete /
        syncCompleteFor (TopologyManager.java:115-186): a shard whose quorum
        has synced unlocks ITS range for precise coordination even while
        other shards of the same epoch are still syncing."""
        if self.sync_complete:
            return True
        synced = []
        complete = True
        for shard in self.global_topology.shards:
            acks = sum(1 for n in shard.nodes if n in self.synced_nodes)
            if acks >= shard.slow_path_quorum_size:
                synced.append(shard.range)
            else:
                complete = False
        self.synced_ranges = Ranges(synced)
        self.sync_complete = complete
        return complete

    def sync_complete_for(self, select) -> bool:
        """Per-range unlock: the selection is fully inside quorum-synced
        shard ranges (TopologyManager.java syncCompleteFor)."""
        if self.sync_complete:
            return True
        if self.synced_ranges.is_empty:
            return False
        return _covered_by(select, self.synced_ranges)


class TopologyManager:
    def __init__(self, node_id: int, sorter=None):
        from accord_tpu.topology.sorter import SIZE_OF_INTERSECTION
        self.node_id = node_id
        self.sorter = sorter if sorter is not None else SIZE_OF_INTERSECTION
        self._epochs: Dict[int, EpochState] = {}
        self._min_epoch = 0
        self._max_epoch = 0
        self._pending: Dict[int, AsyncResult] = {}
        self._fetch_hook: Optional[Callable[[int], None]] = None
        # windows: with_unsynced_epochs calls; extended: windows widened to
        # older epochs; range_unlocks: windows kept precise by the per-range
        # sync test while the epoch as a whole was still syncing
        self.stats = {"windows": 0, "extended": 0, "range_unlocks": 0}

    # -- feeding --
    def on_topology_update(self, topology: Topology) -> None:
        epoch = topology.epoch
        if self._max_epoch == 0:
            self._min_epoch = epoch
            # first epoch needs no predecessor sync
            state = EpochState(topology)
            state.sync_complete = True
            self._epochs[epoch] = state
        else:
            invariants.check_argument(
                epoch == self._max_epoch + 1,
                "topology epochs must arrive in order (%d after %d)",
                epoch, self._max_epoch)
            self._epochs[epoch] = EpochState(topology)
        self._max_epoch = max(self._max_epoch, epoch)
        pending = self._pending.pop(epoch, None)
        if pending is not None:
            pending.try_success(topology)

    def on_epoch_sync_complete(self, node: int, epoch: int) -> None:
        """Peer `node` reports it finished syncing epoch `epoch`'s data."""
        state = self._epochs.get(epoch)
        if state is None:
            return  # unknown epoch; acks for future epochs are re-broadcast
        state.synced_nodes.add(node)
        state.recompute_sync()

    def on_epoch_closed(self, ranges: Ranges, epoch: int) -> None:
        state = self._epochs.get(epoch)
        if state is not None:
            state.closed = state.closed.union(ranges)

    def on_epoch_redundant(self, ranges: Ranges, epoch: int) -> None:
        state = self._epochs.get(epoch)
        if state is not None:
            state.redundant = state.redundant.union(ranges)

    def truncate_before(self, epoch: int) -> None:
        for e in list(self._epochs):
            if e < epoch:
                del self._epochs[e]
        self._min_epoch = max(self._min_epoch, epoch)

    def set_fetch_hook(self, hook: Callable[[int], None]) -> None:
        """Called when someone awaits an epoch we don't know (drives
        ConfigurationService.fetchTopologyForEpoch)."""
        self._fetch_hook = hook

    # -- queries --
    @property
    def epoch(self) -> int:
        return self._max_epoch

    @property
    def min_epoch(self) -> int:
        return self._min_epoch

    def has_epoch(self, epoch: int) -> bool:
        return epoch in self._epochs

    def current(self) -> Topology:
        invariants.check_state(self._max_epoch > 0, "no topology yet")
        return self._epochs[self._max_epoch].global_topology

    def current_local(self) -> Topology:
        return self.current().for_node(self.node_id)

    def for_epoch(self, epoch: int) -> Topology:
        state = self._epochs.get(epoch)
        invariants.check_state(state is not None, "unknown epoch %d", epoch)
        return state.global_topology

    def is_sync_complete(self, epoch: int) -> bool:
        state = self._epochs.get(epoch)
        return state is not None and state.sync_complete

    def epoch_acked_by(self, epoch: int, node: int) -> bool:
        """Has `node` reported sync-complete for `epoch`?  The epoch-install
        gossip uses this to stop resending to peers that have demonstrably
        caught up (a sync ack implies the peer knows the topology)."""
        state = self._epochs.get(epoch)
        return state is not None and node in state.synced_nodes

    def sync_complete_for(self, epoch: int, select) -> bool:
        """Epoch-sync test at range granularity: true when the selection's
        ranges all belong to quorum-synced shards of `epoch`, even if the
        epoch as a whole is still syncing (TopologyManager.syncCompleteFor)."""
        state = self._epochs.get(epoch)
        return state is not None and state.sync_complete_for(select)

    def await_epoch(self, epoch: int) -> AsyncResult:
        """Resolves (with the Topology) once `epoch` is known locally."""
        if epoch in self._epochs:
            return success(self._epochs[epoch].global_topology)
        pending = self._pending.get(epoch)
        if pending is None:
            pending = self._pending[epoch] = AsyncResult()
        if self._fetch_hook is not None:
            # re-trigger on every await: the hook dedupes in-flight fetches
            # itself, and a fetch that failed (source unreachable) must be
            # retriable by the next waiter rather than wedging every one
            self._fetch_hook(epoch)
        return pending

    # -- coordination epoch-window selection --
    def precise_epochs(self, select, min_epoch: int, max_epoch: int) -> Topologies:
        """Sub-topologies for exactly [min_epoch, max_epoch]
        (TopologyManager.preciseEpochs)."""
        out: List[Topology] = []
        for e in range(max_epoch, min_epoch - 1, -1):
            out.append(self.for_epoch(e).for_selection(select))
        return Topologies(out)

    def with_unsynced_epochs(self, select, min_epoch: int, max_epoch: int
                             ) -> Topologies:
        """[min_epoch, max_epoch] extended downward through epochs whose sync
        has not yet quorum-completed FOR THE SELECTION's ranges, so replicas
        still serving old epochs are contacted
        (TopologyManager.withUnsyncedEpochs).  Range-granular: an epoch
        counts as synced when every shard range the selection touches has a
        sync quorum, even while other shards of that epoch are still syncing
        (reference syncCompleteFor, TopologyManager.java:115-186)."""
        self.stats["windows"] += 1
        lo = min_epoch
        range_unlock = False
        while True:
            state = self._epochs.get(lo)
            if state is not None and state.sync_complete_for(select):
                range_unlock = not state.sync_complete
                break
            if lo <= self._min_epoch:
                break
            lo -= 1
        if lo < min_epoch:
            self.stats["extended"] += 1
        elif range_unlock:
            # only a PRECISE window counts as a per-range unlock win
            self.stats["range_unlocks"] += 1
        out: List[Topology] = []
        for e in range(max_epoch, lo - 1, -1):
            out.append(self.for_epoch(e).for_selection(select))
        return Topologies(out)

    def with_open_epochs(self, select, min_epoch: int, max_epoch: int) -> Topologies:
        return self.with_unsynced_epochs(select, min_epoch, max_epoch)
