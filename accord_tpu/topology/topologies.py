"""Topologies: the multi-epoch window a coordination spans.

Reference: accord/topology/Topologies.java (Single/Multi). A transaction
coordinated in epoch C but executing in epoch E > C must contact replicas from
every epoch in [C, E]; Topologies holds those per-epoch (sub-)topologies,
newest first, exactly as the reference orders them.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, List, Sequence, Set, Tuple

from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topology import Topology
from accord_tpu.utils import invariants


class Topologies:
    __slots__ = ("_topologies",)

    def __init__(self, topologies: Sequence[Topology]):
        invariants.check_argument(len(topologies) > 0, "empty Topologies")
        ts = sorted(topologies, key=lambda t: -t.epoch)
        for a, b in zip(ts, ts[1:]):
            invariants.check_argument(a.epoch == b.epoch + 1,
                                      "Topologies epochs must be contiguous")
        self._topologies: Tuple[Topology, ...] = tuple(ts)

    @classmethod
    def single(cls, topology: Topology) -> "Topologies":
        return cls((topology,))

    # -- epoch window --
    @property
    def current_epoch(self) -> int:
        return self._topologies[0].epoch

    @property
    def oldest_epoch(self) -> int:
        return self._topologies[-1].epoch

    @property
    def size(self) -> int:
        return len(self._topologies)

    def current(self) -> Topology:
        return self._topologies[0]

    def get(self, i: int) -> Topology:
        """i-th topology, newest first (reference Topologies.get)."""
        return self._topologies[i]

    def for_epoch(self, epoch: int) -> Topology:
        i = self.current_epoch - epoch
        invariants.check_argument(0 <= i < len(self._topologies),
                                  "epoch %d outside window", epoch)
        return self._topologies[i]

    def for_epochs(self, min_epoch: int, max_epoch: int) -> "Topologies":
        return Topologies([t for t in self._topologies
                           if min_epoch <= t.epoch <= max_epoch])

    def __iter__(self):
        return iter(self._topologies)

    # -- node union --
    def nodes(self) -> FrozenSet[int]:
        out: Set[int] = set()
        for t in self._topologies:
            out.update(t.nodes())
        return frozenset(out)

    def contacts(self, sorter=None) -> List[int]:
        ns = list(self.nodes())
        if sorter is not None:
            return sorter.sort(ns, self)
        return sorted(ns)

    def __eq__(self, other):
        return isinstance(other, Topologies) and self._topologies == other._topologies

    def __hash__(self):
        return hash(self._topologies)

    def __repr__(self):
        return f"Topologies(e{self.oldest_epoch}..e{self.current_epoch})"
