"""GeoProfile: named datacenters, per-node placement, and a deterministic
inter-DC latency matrix keyed by link class (intra / metro / wan).

One profile object serves every host:

  * the sim installs it into SimNetwork (`set_geo`) where the per-(src,dst)
    delay draw replaces the flat default-link draw — still one bounded
    `next_int` per delivery, so runs stay bit-identical per seed;
  * the TCP host reads it from ACCORD_GEO (the JSON spec below) and applies
    the NOMINAL one-way delay as an egress shim on the event loop's own
    scheduler — no `tc`, no root, wall-clock clusters see the same matrix;
  * the obs stack labels coordination outcomes by the coordinator's DC and
    buckets the transport census by `link_class` so WAN crossings/txn and
    WAN bytes/txn are first-class recorded numbers.

Latency bounds are ONE-WAY microseconds; an RTT is the sum of two
independent one-way draws, so `rtt_us(a, b)` (2x the nominal midpoint) is
the number a lane's `p50_rtt_multiple` is expressed against.

Spec (JSON, also the ACCORD_GEO env payload):

    {"name": "wan3",
     "dcs": {"dc_a": [1, 2, 3, 4], "dc_b": [5]},
     "classes": {"intra": [150, 400], "wan": [22500, 27500]},
     "pairs": [["dc_a", "dc_b", "wan", 22500, 27500]]}

`classes` overrides the per-class default one-way bounds; `pairs` assigns a
class and (optionally) bespoke bounds to a specific DC pair — unlisted
cross-DC pairs default to class "wan".
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, Optional, Tuple

# default ONE-WAY bounds (us) per link class; a metro link is a nearby
# facility (~2-5 ms RTT), a wan link a cross-region backbone (~45-55 ms RTT)
DEFAULT_CLASS_BOUNDS_US: Dict[str, Tuple[int, int]] = {
    "intra": (150, 400),
    "metro": (1_500, 2_500),
    "wan": (22_500, 27_500),
}

LINK_CLASSES = ("intra", "metro", "wan")


def _pair_key(dc_a: str, dc_b: str) -> Tuple[str, str]:
    return (dc_a, dc_b) if dc_a <= dc_b else (dc_b, dc_a)


class GeoProfile:
    """Immutable DC layout + latency matrix (see module docstring)."""

    __slots__ = ("name", "dcs", "node_dc", "class_bounds_us",
                 "pair_overrides")

    def __init__(self, dcs: Dict[str, Iterable[int]], name: str = "geo",
                 class_bounds_us: Optional[Dict[str, Tuple[int, int]]] = None,
                 pairs: Optional[Iterable[Tuple]] = None):
        self.name = str(name)
        self.dcs: Dict[str, Tuple[int, ...]] = {
            str(dc): tuple(sorted(int(n) for n in nodes))
            for dc, nodes in dcs.items()}
        self.node_dc: Dict[int, str] = {}
        for dc, nodes in self.dcs.items():
            for n in nodes:
                if n in self.node_dc:
                    raise ValueError(f"node {n} assigned to both "
                                     f"{self.node_dc[n]} and {dc}")
                self.node_dc[n] = dc
        self.class_bounds_us: Dict[str, Tuple[int, int]] = dict(
            DEFAULT_CLASS_BOUNDS_US)
        for cls, bounds in (class_bounds_us or {}).items():
            lo, hi = int(bounds[0]), int(bounds[1])
            self.class_bounds_us[str(cls)] = (lo, hi)
        # (dc, dc) sorted pair -> (class, lo_us, hi_us)
        self.pair_overrides: Dict[Tuple[str, str], Tuple[str, int, int]] = {}
        for entry in (pairs or ()):
            dc_a, dc_b, cls = str(entry[0]), str(entry[1]), str(entry[2])
            if len(entry) >= 5:
                lo, hi = int(entry[3]), int(entry[4])
            else:
                lo, hi = self.class_bounds_us[cls]
            self.pair_overrides[_pair_key(dc_a, dc_b)] = (cls, lo, hi)

    # ------------------------------------------------------------ queries --
    def dc_of(self, node_id: int) -> Optional[str]:
        return self.node_dc.get(node_id)

    def nodes_in(self, dc: str) -> Tuple[int, ...]:
        return self.dcs.get(dc, ())

    def link_class(self, src: int, dst: int) -> Optional[str]:
        """intra | metro | wan — None when either endpoint is unplaced
        (the caller falls back to its flat default behavior)."""
        a, b = self.node_dc.get(src), self.node_dc.get(dst)
        if a is None or b is None:
            return None
        if a == b:
            return "intra"
        over = self.pair_overrides.get(_pair_key(a, b))
        return over[0] if over is not None else "wan"

    def delay_bounds_us(self, src: int, dst: int
                        ) -> Optional[Tuple[int, int]]:
        """One-way (lo, hi) us for this ordered pair; None when unplaced."""
        a, b = self.node_dc.get(src), self.node_dc.get(dst)
        if a is None or b is None:
            return None
        if a == b:
            return self.class_bounds_us["intra"]
        over = self.pair_overrides.get(_pair_key(a, b))
        if over is not None:
            return (over[1], over[2])
        return self.class_bounds_us["wan"]

    def one_way_nominal_us(self, src: int, dst: int) -> Optional[int]:
        """Midpoint one-way delay — the TCP shim's constant per-pair delay
        (constant per pair keeps per-lane frame order trivially intact)."""
        bounds = self.delay_bounds_us(src, dst)
        return (bounds[0] + bounds[1]) // 2 if bounds is not None else None

    def rtt_us(self, dc_a: str, dc_b: str) -> int:
        """Nominal RTT between two DCs: 2x the midpoint one-way delay.
        This is the 'injected WAN RTT' a lane's latency multiples cite."""
        if dc_a == dc_b:
            lo, hi = self.class_bounds_us["intra"]
        else:
            over = self.pair_overrides.get(_pair_key(dc_a, dc_b))
            lo, hi = (over[1], over[2]) if over is not None \
                else self.class_bounds_us["wan"]
        return 2 * ((lo + hi) // 2)

    # ------------------------------------------------------------- codecs --
    def to_spec(self) -> dict:
        """JSON-friendly spec (the ACCORD_GEO env payload)."""
        return {
            "name": self.name,
            "dcs": {dc: list(nodes) for dc, nodes in sorted(self.dcs.items())},
            "classes": {cls: list(b) for cls, b
                        in sorted(self.class_bounds_us.items())},
            "pairs": [[a, b, cls, lo, hi] for (a, b), (cls, lo, hi)
                      in sorted(self.pair_overrides.items())],
        }

    @classmethod
    def from_spec(cls, spec: dict) -> "GeoProfile":
        return cls(spec["dcs"], name=spec.get("name", "geo"),
                   class_bounds_us=spec.get("classes"),
                   pairs=spec.get("pairs"))

    @classmethod
    def from_env(cls, value: Optional[str]) -> Optional["GeoProfile"]:
        """Parse the ACCORD_GEO env payload (JSON spec, or empty/None)."""
        if not value:
            return None
        return cls.from_spec(json.loads(value))

    def to_wire(self) -> tuple:
        """Canonical nested-tuple form for EpochInstall frames (wire.py's
        structural codec round-trips tuples of str/int losslessly)."""
        return (
            self.name,
            tuple((dc, tuple(nodes))
                  for dc, nodes in sorted(self.dcs.items())),
            tuple((cls, int(lo), int(hi)) for cls, (lo, hi)
                  in sorted(self.class_bounds_us.items())),
            tuple((a, b, cls, int(lo), int(hi))
                  for (a, b), (cls, lo, hi)
                  in sorted(self.pair_overrides.items())),
        )

    @classmethod
    def from_wire(cls, wire) -> "GeoProfile":
        name, dcs, classes, pairs = wire
        return cls({dc: nodes for dc, nodes in dcs}, name=name,
                   class_bounds_us={c: (lo, hi) for c, lo, hi in classes},
                   pairs=pairs)

    def __eq__(self, other) -> bool:
        return isinstance(other, GeoProfile) and \
            self.to_wire() == other.to_wire()

    def __repr__(self) -> str:
        return (f"GeoProfile({self.name!r}, dcs="
                f"{{{', '.join(f'{d}:{len(n)}' for d, n in sorted(self.dcs.items()))}}})")


def wan3_profile(hub: int = 4) -> GeoProfile:
    """The slo-wan lane's layout: a hub DC holding a full slow-path quorum
    (`hub` nodes) plus three single-node DCs at increasing WAN distance —
    RTT ~50 ms (dc_b), ~100 ms (dc_c), ~160 ms (dc_d) from the hub.

    With rf = hub + 3 the slow-path/stable quorum (rf - f) fits inside the
    hub, so the client-visible latency is governed by how far the fast-path
    ELECTORATE reaches: a minimal electorate spanning to dc_b commits in
    ~1x the dc_a<->dc_b RTT, while the all-replicas electorate's larger
    fast quorum must additionally hear dc_c — measurably worse."""
    n = int(hub)
    return GeoProfile(
        dcs={"dc_a": range(1, n + 1), "dc_b": (n + 1,),
             "dc_c": (n + 2,), "dc_d": (n + 3,)},
        name="wan3",
        pairs=[
            ("dc_a", "dc_b", "wan", 22_500, 27_500),   # RTT ~50 ms
            ("dc_a", "dc_c", "wan", 45_000, 55_000),   # RTT ~100 ms
            ("dc_a", "dc_d", "wan", 75_000, 85_000),   # RTT ~160 ms
            ("dc_b", "dc_c", "wan", 35_000, 45_000),
            ("dc_b", "dc_d", "wan", 55_000, 65_000),
            ("dc_c", "dc_d", "wan", 45_000, 55_000),
        ])
