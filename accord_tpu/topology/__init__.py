"""Topology / membership layer (reference: accord/topology — SURVEY.md §2.6)."""

from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topology import Topology
from accord_tpu.topology.topologies import Topologies
from accord_tpu.topology.manager import TopologyManager
