"""SimpleProgressLog: the timeout-driven liveness engine.

Reference: accord/impl/SimpleProgressLog.java:77-714 — a per-CommandStore
instance polled on a recurring schedule (run loop :669); per-txn home-shard
state machine escalating through Expected -> NoProgress -> Investigating to
`Node.maybeRecover`, and a BlockedState chasing commits/applies of
dependencies a local command is stuck behind.

Every replica of the home shard monitors a txn (they dedup through
`Node.coordinating` and ballot preemption); blocked dependencies are chased by
whichever store is waiting on them.

State-machine mapping vs the reference (r4 depth audit, VERDICT item 9):

* CoordinateState Expected/NoProgress ladder -> _HomeState.attempts with
  linearly-spaced deadlines (_check_home): no escalation before a full
  grace period of no observed ProgressToken advance, exactly the
  reference's "only if nothing changed since the last poll" rule
  (:NoProgress).  Investigating -> the CheckStatus probe _check_home
  issues BEFORE recovering (_done_home consumes the merged token and
  only escalates to Node.recover when the quorum shows no one else
  progressed) — the reference's Investigate round is this same
  probe-then-decide step.
* Done/Durable standdown -> update()/durable() popping the home entry on
  durability; the InformHomeDurable chase-path short-circuit covers the
  lost-broadcast case.
* NonHomeState (the reference's per-replica ensure-stable nudging) is
  deliberately absorbed into _BlockedState: a non-home replica only acts
  when something local WAITS (waiting()), and its escalation ladder
  (maybe_execute nudge -> root-blocker walk -> fetch_data x2 -> recover)
  subsumes StillUnused/Safe transitions; the burn's recovery-storm cap
  (test_burn_hostile.test_burn_recovery_storm_bounded, 25% loss)
  asserts the ladder cannot mask livelock by retrying forever.
* Blocked disambiguation by blockedUntil (HasCommit/HasApply; :486) ->
  _BlockedState.until "Committed"/"Applied" with _blocked_satisfied.

Infer ladder (coordinate/infer.py): both escalation paths prefer the
quorum-inferred commit-invalidate over the multi-shard Invalidate round —
_check_home's maybe_recover and _check_blocked's fetch_data each fold the
per-reply InvalidIf evidence and, on a per-shard quorum of it, commit the
invalidation with NO extra round (infer.infer_invalid_with_quorum);
coordinate/invalidate.py remains the ballot-settled fallback for
sub-quorum evidence, witnessed Accepts, and ACCORD_INFER_FULL=0.
"""

from __future__ import annotations

from typing import Dict, Optional

from accord_tpu.api.spi import ProgressLog
from accord_tpu.local.status import ProgressToken, SaveStatus
from accord_tpu.primitives.keys import Route
from accord_tpu.primitives.timestamp import TxnId

# escalation backoff cap: attempts space retries out linearly, but repair
# latency after a long partition must stay bounded — a chain of
# dependency fetches otherwise takes (attempts x grace) per link to heal
_MAX_BACKOFF_STEPS = 8


class _HomeState:
    """Progress tracking for a txn this store is home for
    (SimpleProgressLog.CoordinateState)."""

    __slots__ = ("txn_id", "route", "token", "updated_at_s", "attempts",
                 "investigating")

    def __init__(self, txn_id: TxnId, route: Optional[Route],
                 token: ProgressToken, now_s: float):
        self.txn_id = txn_id
        self.route = route
        self.token = token
        self.updated_at_s = now_s
        self.attempts = 0
        self.investigating = False


def _token_of(command) -> ProgressToken:
    return ProgressToken.of(command.durability, command.save_status,
                            command.promised, command.accepted_ballot)


class _BlockedState:
    """A local command is stuck waiting for `txn_id` to reach `blocked_until`
    (SimpleProgressLog.BlockedState)."""

    __slots__ = ("txn_id", "route", "blocked_until", "since_s", "attempts",
                 "participants")

    def __init__(self, txn_id: TxnId, route: Optional[Route],
                 blocked_until: str, now_s: float, participants=None):
        self.txn_id = txn_id
        self.route = route
        self.blocked_until = blocked_until
        self.since_s = now_s
        self.attempts = 0
        self.participants = participants  # keys/ranges we learned it through


class SimpleProgressLog(ProgressLog):
    def __init__(self, node, store):
        self.node = node
        self.store = store
        self.home: Dict[TxnId, _HomeState] = {}
        self.blocked: Dict[TxnId, _BlockedState] = {}
        self._informed_home: set = set()
        delay = node.config.progress_log_schedule_delay_s
        self._delay_s = delay
        # stagger replicas so they do not duel over recovery ballots
        self._grace_s = 2 * delay + node.random.next_float() * delay
        self._task = node.scheduler.recurring(delay, self._run)

    # ----------------------------------------------------- state callbacks --
    def update(self, store, txn_id: TxnId, command) -> None:
        now = self._now_s()
        # home monitoring stands down once the outcome is durable anywhere;
        # blocked entries are LOCAL waits and clear only when locally
        # satisfied (majority durability elsewhere doesn't apply us)
        if command.is_applied_or_gone or command.durability.is_durable:
            self.home.pop(txn_id, None)
        blocked = self.blocked.get(txn_id)
        if blocked is not None and _blocked_satisfied(command, blocked):
            self.blocked.pop(txn_id, None)
        if command.is_applied_or_gone or command.durability.is_durable:
            return
        if not self._is_home(command):
            return
        state = self.home.get(txn_id)
        token = _token_of(command)
        if state is None:
            self.home[txn_id] = _HomeState(txn_id, command.route, token, now)
        elif token > state.token:
            # movement — durability, phase, or a fresh promise — resets the
            # escalation backoff.  Raise-only: state.token may hold a
            # REMOTELY-observed ballot floor absorbed by _done_home that no
            # local token can contain (Propagate never applies ballots);
            # lowering it would re-read that stale remote ballot as fresh
            # progress on every probe.  Ballot ranks below status in the
            # token order, so genuine local progress still raises the floor.
            state.token = token
            state.route = command.route or state.route
            state.updated_at_s = now
            state.attempts = 0
            state.investigating = False

    def waiting(self, blocked_by: TxnId, store, blocked_until: str,
                route, participants) -> None:
        if blocked_by in self.blocked:
            return
        cmd = self.store.commands.get(blocked_by)
        r = route if route is not None else (cmd.route if cmd else None)
        self.blocked[blocked_by] = _BlockedState(blocked_by, r, blocked_until,
                                                 self._now_s(), participants)

    def durable(self, command) -> None:
        if command.durability.is_durable:
            self.home.pop(command.txn_id, None)
            # blocked waits are local; see update()
            if command.txn_id in self.blocked and not self._is_home(command):
                # home short-circuit (InformHomeDurable.java:30), CHASE
                # path only: we were blocked on this txn and durability
                # arrived while the system was degraded — the home shard's
                # monitor may have missed its own broadcast and still be
                # chasing a settled txn; re-inform it (once per txn).  The
                # happy path (durability via the Persist tail's broadcast,
                # no local chase) never sends: home got the same broadcast.
                self._inform_home_durable(command)

    def _inform_home_durable(self, command) -> None:
        txn_id = command.txn_id
        route = command.route
        if route is None or txn_id in self._informed_home:
            return
        self._informed_home.add(txn_id)
        from accord_tpu.messages.durability import InformHomeDurable
        from accord_tpu.primitives.keys import Route, RoutingKeys
        home_route = Route(route.home_key,
                           keys=RoutingKeys([route.home_key]),
                           is_full=False)
        durability = command.durability
        execute_at = command.execute_at
        self.node.send_to_route(
            home_route, txn_id.epoch, txn_id.epoch,
            lambda to, scope: InformHomeDurable(txn_id, scope, execute_at,
                                                durability))

    def clear(self, txn_id: TxnId) -> None:
        self.home.pop(txn_id, None)
        self.blocked.pop(txn_id, None)
        self._informed_home.discard(txn_id)

    # -------------------------------------------------------------- polling --
    def _escalation(self, txn_id: TxnId, what: str, attempts: int) -> None:
        """Flight-recorder breadcrumb (obs/flight.py): every escalation the
        liveness engine takes lands on the node's ring, so a post-mortem
        shows WHY a recovery/fetch round started, not just that it did."""
        obs = getattr(self.node, "obs", None)
        if obs is not None:
            obs.flight.record("escalate", repr(txn_id),
                              (self.store.id, what, attempts))

    def _run(self) -> None:
        now = self._now_s()
        for state in list(self.home.values()):
            self._check_home(state, now)
        for state in list(self.blocked.values()):
            self._check_blocked(state, now)
        if self.store.gated:
            # renew per-key execution-gate chases (a gate's first blocker
            # may have resolved with others remaining) — commands.py
            from accord_tpu.local.commands import sweep_key_gates
            from accord_tpu.local.store import PreLoadContext
            self.store.execute(
                PreLoadContext.empty(),
                lambda safe: sweep_key_gates(safe))

    def _check_home(self, state: _HomeState, now: float) -> None:
        if state.investigating:
            return
        deadline = state.updated_at_s \
            + self._grace_s * (1 + min(state.attempts, _MAX_BACKOFF_STEPS))
        if now < deadline:
            return
        if state.route is None:
            return
        state.investigating = True
        state.attempts += 1
        self._escalation(state.txn_id, "investigate_home", state.attempts)
        # first ask the home shard whether anyone progressed; only escalate
        # to a recovery ballot if nobody did (MaybeRecover.java)
        from accord_tpu.coordinate.fetch import maybe_recover
        maybe_recover(self.node, state.txn_id, state.route,
                      state.token).add_callback(
            lambda v, f: self._done_home(state, v))

    def _done_home(self, state: _HomeState, observed=None) -> None:
        state.investigating = False
        state.updated_at_s = self._now_s()
        # Absorb remotely-observed movement: Propagate applies status and
        # outcome knowledge but never ballots, so a dead coordinator's
        # promise would read as fresh "progress" on EVERY poll and the txn
        # would never escalate to Recover.  Raising our comparison floor to
        # the observed token means an unchanged remote state compares equal
        # next poll and recovery proceeds (MaybeRecover.hasMadeProgress
        # records the observed ProgressToken the same way).
        if observed is not None and hasattr(observed, "to_progress_token"):
            token = observed.to_progress_token()
            if token > state.token:
                state.token = token

    def _walk_to_root_blocker(self, txn_id: TxnId) -> TxnId:
        """Follow the WaitingOn chain to the lowest unresolved dependency
        (the reference's waiting-chain walker, SimpleProgressLog.java:77-714
        following Command.WaitingOn bitsets): fetching/recovering a command
        that is merely waiting on ITS deps achieves nothing — the root
        blocker is what needs chasing."""
        seen = set()
        cur_id = txn_id
        for _ in range(64):
            if cur_id in seen:
                break
            seen.add(cur_id)
            cmd = self.store.commands.get(cur_id)
            if cmd is None or cmd.waiting_on is None \
                    or not cmd.waiting_on.is_waiting:
                break
            nxt = cmd.waiting_on.next_waiting()
            if nxt is None:
                break
            cur_id = nxt
        return cur_id

    def _check_blocked(self, state: _BlockedState, now: float) -> None:
        cmd = self.store.commands.get(state.txn_id)
        if cmd is not None and _blocked_satisfied(cmd, state):
            self.blocked.pop(state.txn_id, None)
            return
        deadline = state.since_s \
            + self._grace_s * (1 + min(state.attempts, _MAX_BACKOFF_STEPS))
        if now < deadline:
            return
        # a runnable command that merely missed its notification needs a
        # nudge, not a fetch
        if cmd is not None and cmd.save_status in (SaveStatus.STABLE,
                                                   SaveStatus.PRE_APPLIED) \
                and (cmd.waiting_on is None or not cmd.waiting_on.is_waiting):
            from accord_tpu.local import commands as C
            from accord_tpu.local.store import PreLoadContext
            state.since_s = now
            self._escalation(state.txn_id, "nudge_execute", state.attempts)
            self.store.execute(PreLoadContext.for_txn(state.txn_id),
                               lambda s: C.maybe_execute(
                                   s, s.get(state.txn_id), False))
            return
        # chase the bottom of the waiting chain, not the middle
        root = self._walk_to_root_blocker(state.txn_id)
        if root != state.txn_id and root not in self.blocked:
            self._escalation(root, "chase_root_blocker", state.attempts)
            root_cmd = self.store.commands.get(root)
            until = ("Applied" if root_cmd is not None
                     and root_cmd.has_been(SaveStatus.COMMITTED)
                     else "Committed")
            self.blocked[root] = _BlockedState(
                root, root_cmd.route if root_cmd is not None else None,
                until, now - self._grace_s,  # due immediately
                participants=state.participants)
            return
        route = state.route or (cmd.route if cmd is not None else None)
        from accord_tpu.coordinate.fetch import fetch_data, find_route
        if route is None:
            # learn the route through the participants that recorded the dep;
            # discovery polls do not consume the cheap-fetch budget, and a
            # learned route starts the escalation ladder from the bottom
            state.since_s = now
            if state.participants is None or len(state.participants) == 0:
                return
            def learned(merged, failure, state=state):
                if failure is None and merged is not None \
                        and merged.route is not None:
                    state.route = merged.route
                    state.attempts = 0
            self._escalation(state.txn_id, "find_route", state.attempts)
            find_route(self.node, state.txn_id,
                       state.participants).add_callback(learned)
            return
        from accord_tpu.coordinate.infer import full_infer_enabled
        state.attempts += 1
        state.since_s = now
        if state.attempts <= 2 or (state.attempts % 2 == 1
                                   and full_infer_enabled()):
            # cheap path first: pull the missing commit/apply from its
            # shards — under the full Infer ladder this fetch ALSO settles
            # a durability-fenced straggler outright (quorum InvalidIf
            # evidence -> commit-invalidate, or a truncated-remotely dep
            # installed as a local truncation), so the blocked chase never
            # reaches the recover/Invalidate tier.  Under the full
            # ladder, fetches stay INTERLEAVED past the recovery tier
            # (odd attempts): the Propagate catch-up ladders (local
            # truncation install; INSUFFICIENT + erased deps -> staleness
            # escalation after 3 strikes) are driven by fetches, and
            # recovery of an already-truncated txn succeeds without
            # repairing the local copy — the r5 fetch-twice-then-recover-
            # forever ladder left them unreachable (=0 keeps it)
            self._escalation(state.txn_id, "fetch_data", state.attempts)
            fetch_data(self.node, state.txn_id, route)
        else:
            # still stuck: the txn itself may be undecided — recover it
            self._escalation(state.txn_id, "recover", state.attempts)
            self._recover(state.txn_id, route, lambda: None)

    def _recover(self, txn_id: TxnId, route: Route, on_settled) -> None:
        result = self.node.recover(txn_id, route)

        def finished(value, failure):
            on_settled()

        result.add_callback(finished)

    def _is_home(self, command) -> bool:
        return (command.route is not None
                and not self.store.ranges.is_empty
                and self.store.ranges.contains(command.route.home_key))

    def _now_s(self) -> float:
        return self.node.now_us() / 1e6


def _blocked_satisfied(command, state: _BlockedState) -> bool:
    if command.is_applied_or_gone or command.is_truncated:
        return True
    if state.blocked_until == "Committed":
        return command.has_been(SaveStatus.COMMITTED)
    if state.blocked_until == "Applied":
        return command.has_been(SaveStatus.APPLIED)
    return command.route is not None  # 'HasRoute'
