"""Append-only list-register data plane — the reference workload implementation.

Reference: the maelstrom data plane (accord-maelstrom Maelstrom{Read,Write,
Update,Query,Result,Data}, Datum.java:30, MaelstromUpdate.java:40-47): a
multi-key KV where each key holds an append-only list of ints; reads return
the list, updates append. This is the workload the burn test's strict
serializability verifier checks (monotonic per-key append sequences).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from accord_tpu.api.data import Data, Query, Read, Result, Update, Write
from accord_tpu.api.spi import DataStore
from accord_tpu.primitives.keys import Key, Keys, Ranges
from accord_tpu.primitives.timestamp import Timestamp, TxnId
from accord_tpu.utils.async_chains import AsyncResult, success


class ListStore(DataStore):
    """key -> executeAt-ordered list of (timestamp, value) appends.

    Values carry their executeAt so replay is exactly idempotent and
    bootstrap snapshots MERGE rather than replace: a rejoining replica that
    missed one mid-history write still heals it even when its latest write
    matches the source's (a last-timestamp guard would skip the whole key
    and silently lose the gap)."""

    def __init__(self, node_id: int = 0):
        self.node_id = node_id
        self.data: Dict[Key, List[Tuple[Timestamp, int]]] = {}

    def get(self, key: Key) -> Tuple[int, ...]:
        return tuple(v for _, v in self.data.get(key, ()))

    def append(self, key: Key, value: int, at: Timestamp) -> None:
        entries = self.data.setdefault(key, [])
        lo, hi = 0, len(entries)
        while lo < hi:
            mid = (lo + hi) // 2
            if entries[mid][0] < at:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(entries) and entries[lo][0] == at:
            return  # replay
        entries.insert(lo, (at, value))

    def keys_in(self, ranges: Ranges) -> List[Key]:
        """Data keys present within `ranges` (range-scan support; the
        reference's maelstrom store is a sorted TreeMap serving the same
        query, MaelstromStore)."""
        return sorted(k for k in self.data if ranges.contains(k))

    def snapshot(self) -> Dict[int, Tuple[int, ...]]:
        return {k.token: self.get(k) for k in self.data}

    # -- bootstrap snapshot transfer --
    def snapshot_ranges(self, ranges: Ranges):
        return {k: tuple(self.data[k]) for k in self.keys_in(ranges)}

    def install_snapshot(self, snapshot) -> None:
        for k, entries in snapshot.items():
            for at, value in entries:
                self.append(k, value, at)


class ListData(Data):
    def __init__(self, values: Dict[Key, Tuple[int, ...]]):
        self.values = dict(values)

    def merge(self, other: "Data") -> "Data":
        merged = dict(self.values)
        merged.update(other.values)  # type: ignore[attr-defined]
        return ListData(merged)

    def __eq__(self, other):
        return isinstance(other, ListData) and self.values == other.values

    def __repr__(self):
        return f"ListData({ {k.token: v for k, v in self.values.items()} })"


class ListRead(Read):
    def __init__(self, keys: Keys):
        self._keys = keys

    def keys(self) -> Keys:
        return self._keys

    def read(self, key: Key, execute_at: Timestamp, store: ListStore
             ) -> AsyncResult[Data]:
        return success(ListData({key: store.get(key)}))

    def slice(self, ranges: Ranges) -> "ListRead":
        return ListRead(self._keys.slice(ranges))

    def merge(self, other: "ListRead") -> "ListRead":
        return ListRead(self._keys.with_(other._keys))

    def __eq__(self, other):
        return isinstance(other, ListRead) and self._keys == other._keys

    def __repr__(self):
        return f"ListRead({self._keys!r})"


class ListRangeRead(Read):
    """Range-domain read: scans every key present in the ranges at execute
    time (the reference's range queries through the same Read port — Read.java
    read(Seekable, ...) where the Seekable is a Range)."""

    def __init__(self, ranges: Ranges):
        self._ranges = ranges

    def keys(self) -> Ranges:
        return self._ranges

    def read(self, rng, execute_at: Timestamp, store: ListStore
             ) -> AsyncResult[Data]:
        covered = Ranges([rng]) if not isinstance(rng, Ranges) else rng
        return success(ListData({k: store.get(k)
                                 for k in store.keys_in(covered)}))

    def slice(self, ranges: Ranges) -> "ListRangeRead":
        return ListRangeRead(self._ranges.slice(ranges))

    def merge(self, other: "ListRangeRead") -> "ListRangeRead":
        return ListRangeRead(self._ranges.union(other._ranges))

    def __eq__(self, other):
        return isinstance(other, ListRangeRead) and self._ranges == other._ranges

    def __repr__(self):
        return f"ListRangeRead({self._ranges!r})"


class ListWrite(Write):
    def __init__(self, appends: Dict[Key, int]):
        self.appends = dict(appends)

    def apply(self, key: Key, execute_at: Timestamp, store: ListStore
              ) -> AsyncResult[None]:
        if key in self.appends:
            store.append(key, self.appends[key], execute_at)
        return success(None)

    def __repr__(self):
        return f"ListWrite({ {k.token: v for k, v in self.appends.items()} })"


class ListUpdate(Update):
    def __init__(self, appends: Dict[Key, int]):
        self.appends = dict(appends)

    def keys(self) -> Keys:
        return Keys(self.appends.keys())

    def apply(self, execute_at: Timestamp, data: Optional[Data]) -> Write:
        return ListWrite(self.appends)

    def slice(self, ranges: Ranges) -> "ListUpdate":
        return ListUpdate({k: v for k, v in self.appends.items()
                           if ranges.contains(k)})

    def merge(self, other: "ListUpdate") -> "ListUpdate":
        merged = dict(self.appends)
        merged.update(other.appends)
        return ListUpdate(merged)

    def __eq__(self, other):
        return isinstance(other, ListUpdate) and self.appends == other.appends

    def __repr__(self):
        return f"ListUpdate({ {k.token: v for k, v in self.appends.items()} })"


class ListResult(Result):
    def __init__(self, txn_id: TxnId, execute_at: Timestamp,
                 read_values: Dict[Key, Tuple[int, ...]],
                 appends: Dict[Key, int]):
        self.txn_id = txn_id
        self.execute_at = execute_at
        self.read_values = dict(read_values)
        self.appends = dict(appends)

    def __eq__(self, other):
        return (isinstance(other, ListResult) and self.txn_id == other.txn_id
                and self.read_values == other.read_values
                and self.appends == other.appends)

    def __repr__(self):
        return (f"ListResult({self.txn_id!r}: "
                f"read={ {k.token: v for k, v in self.read_values.items()} }, "
                f"appended={ {k.token: v for k, v in self.appends.items()} })")


class ListQuery(Query):
    def __eq__(self, other):
        return isinstance(other, ListQuery)  # stateless

    def __hash__(self):
        return hash(ListQuery)

    def compute(self, txn_id: TxnId, execute_at: Timestamp,
                data: Optional[Data], read: Optional[Read],
                update: Optional[Update]) -> Result:
        values = data.values if isinstance(data, ListData) else {}
        appends = update.appends if isinstance(update, ListUpdate) else {}
        return ListResult(txn_id, execute_at, values, appends)
