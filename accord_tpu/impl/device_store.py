"""DeviceCommandStore: the batched deps kernel on the protocol path.

This is the thesis of the port (SURVEY §7 step 7): a CommandStore that
implements the SafeCommandStore active-conflict query by *batching* — incoming
operations accumulate in a flush window; one XLA call computes every declared
deps scan for the whole window (ops.deps_kernel.batched_active_deps, the
device formulation of CommandsForKey.mapReduceActive, reference
accord/local/CommandsForKey.java:614-650); operations then execute serially,
serving their scans from the precomputed masks.

Equivalence contract: results must be bit-identical to the scalar path.  Two
mechanisms enforce it:

  * snapshot validation — each CommandsForKey carries a version counter; a
    precomputed probe is served only if every key it covers is unchanged
    since the snapshot, with one exception: a single bump whose mutator is
    the querying txn itself (its own preaccept/accept registration, which
    the scan excludes anyway).  Anything else — an earlier op in the same
    window mutating a shared key, a truncation, an unmanaged notification —
    falls back to the scalar scan.  Correctness never depends on the device
    result being fresh.
  * verify mode — every served scan is cross-checked against the scalar scan
    inline and asserted identical; the burn equivalence tests run with this
    on, so a whole hostile-cluster run certifies bit-identity at every query.

The range-command arm (RangeDeps tier) is device-served too: each window
stabs the live range-command index with every declared probe in one [Q, N]
kernel call (ops/range_kernel.py), version-gated on CommandStore.
range_version — any register/cleanup mutation since the snapshot falls back
to the scalar walk — with the activity filter and overlap computation
re-run live over the kernel-pruned candidates.  And execution ordering is
device-planned: windows holding several Applies are scheduled by the
wavefront kernel (ops/wavefront.py) in Kahn-layer order, with the scalar
WaitingOn machinery still gating every transition (see _plan_waves).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from accord_tpu.local.store import (CommandStore, PreLoadContext,
                                    SafeCommandStore)
from accord_tpu.obs.views import CounterDict, MetricView, bind_metric_views
from accord_tpu.primitives.keys import Key, Keys, Ranges
from accord_tpu.primitives.timestamp import KindSet, Timestamp, TxnId


class _Probe:
    """One precomputed active-scan: deps per key at (before, kinds), plus the
    snapshot versions that gate serving it."""

    __slots__ = ("before", "kinds", "keyed", "key_set", "versions",
                 "committed_versions")

    def __init__(self, before: Timestamp, kinds: KindSet,
                 keyed: Dict[Key, List[TxnId]], key_set: Set[Key],
                 versions: Dict[Key, int], committed_versions: Dict[Key, int]):
        self.before = before
        self.kinds = kinds
        self.keyed = keyed
        self.key_set = key_set
        self.versions = versions
        self.committed_versions = committed_versions


class _RangeProbe:
    """One precomputed range-command stab (ops/range_kernel.py): the
    kernel-pruned candidate set of range txns geometrically intersecting
    the probe's participants.  Serving re-runs ONLY the scalar activity
    filter and overlap computation over the candidates (cost proportional
    to matches, not to the live range-command population).  Version-gated
    on CommandStore.range_version (any register/cleanup mutation since the
    snapshot falls back to the scalar walk)."""

    __slots__ = ("before", "kinds", "mode", "owned_repr", "candidates",
                 "version", "log_len")

    def __init__(self, before: Timestamp, kinds: KindSet, mode: str,
                 owned_repr, candidates: Tuple[TxnId, ...], version: int,
                 log_len: int = 0):
        self.before = before
        self.kinds = kinds
        self.mode = mode            # "keys" | "ranges"
        self.owned_repr = owned_repr
        self.candidates = candidates
        self.version = version
        self.log_len = log_len      # range_log length at snapshot


class _RecoveryProbe:
    """One precomputed recovery-scan set (the four mapReduceFull predicates
    of BeginRecovery, ops/recovery_kernel.py) for one probe txn: per-key id
    lists, servable over any subset of the covered keys.  Version gating is
    EXACT (no self-bump tolerance): a first-witness registration inserts the
    probe into other entries' missing[], which changes the scalar answers."""

    __slots__ = ("txn_id", "rejects_a", "rejects_b", "witness", "no_witness",
                 "key_set", "versions")

    def __init__(self, txn_id: TxnId, rejects_a, rejects_b, witness,
                 no_witness, key_set: Set[Key], versions: Dict[Key, int]):
        self.txn_id = txn_id
        self.rejects_a = rejects_a        # {key: [ids]} — any() => reject
        self.rejects_b = rejects_b
        self.witness = witness
        self.no_witness = no_witness
        self.key_set = key_set
        self.versions = versions


# trivially-servable recovery probe for participants with no CFK state:
# every predicate tier is empty, nothing to scan
_EMPTY_RECOVERY = _RecoveryProbe(None, {}, {}, {}, {}, set(), {})


class DeviceSafeCommandStore(SafeCommandStore):
    def map_reduce_active(self, participants, before: Timestamp,
                          kinds: KindSet, fn, on_range_dep=None,
                          exclude: Optional[TxnId] = None) -> None:
        store: DeviceCommandStore = self.store
        probe = store._precomputed.get((before, kinds))
        is_range = isinstance(participants, Ranges)
        owned = self._owned_participants(participants)
        # range-domain participants: the per-key tier is the CFK walk over
        # keys inside the ranges — the probe precomputed exactly that set
        # at snapshot time (see _collect_deps_probes); any key born since
        # fails the cover check below and falls back to scalar
        keys = self._owned_cfk_keys(owned) if is_range else owned
        if probe is None:
            if len(keys) == 0:
                # nothing in the per-key tier to scan (the collection skips
                # empty-owned probes for the same reason): served trivially,
                # only the range-conflict arm remains
                store.device_hits += 1
                self._map_range_conflicts(owned, is_range, before, kinds,
                                          fn, on_range_dep)
                return
            store.device_misses += 1
            store.device_miss_causes["no_probe"] += 1
            return super().map_reduce_active(participants, before, kinds, fn,
                                             on_range_dep, exclude)
        if not all(k in probe.key_set and self._version_ok(k, probe, exclude)
                   for k in keys):
            store.device_misses += 1
            store.device_miss_causes[
                "version" if all(k in probe.key_set for k in keys)
                else "key_cover"] += 1
            return super().map_reduce_active(participants, before, kinds, fn,
                                             on_range_dep, exclude)
        store.device_hits += 1
        if store.verify:
            self._verify_against_scalar(keys, before, kinds, exclude, probe)
        for key in keys:
            for dep in probe.keyed.get(key, ()):
                if dep != exclude:
                    fn(key, dep)
        self._map_range_conflicts(owned, is_range, before, kinds, fn,
                                  on_range_dep)

    # ------------------------------------------------- range-conflict arm --
    def _map_range_conflicts(self, owned, is_range: bool, before: Timestamp,
                             kinds: KindSet, fn, on_range_dep) -> None:
        """Serve the range-command arm from the window's batched stab
        (ops/range_kernel.py) when a version-valid probe covers the query;
        the activity filter and overlap computation re-run live over the
        kernel-pruned candidates only."""
        store: DeviceCommandStore = self.store
        if not store.range_commands:
            return  # scalar walk over an empty index is a no-op
        probe = store._precomputed_ranges.get((before, kinds))
        ok = probe is not None
        if ok:
            if is_range:
                ok = probe.mode == "ranges" and probe.owned_repr == tuple(
                    (r.start, r.end) for r in owned)
            else:
                ok = probe.mode == "keys" and all(
                    k.token in probe.owned_repr for k in owned)
        if not ok:
            store.device_range_misses += 1
            return super()._map_range_conflicts(owned, is_range, before,
                                                kinds, fn, on_range_dep)
        if probe.version != store.range_version:
            if store.range_log is None:
                # delta unavailable (tier disabled mid-window): stale probe
                # is unservable
                store.device_range_misses += 1
                return super()._map_range_conflicts(owned, is_range, before,
                                                    kinds, fn, on_range_dep)
            # the index mutated since the snapshot.  Deletions are safe —
            # the live activity/overlap filters below drop them — and every
            # addition or re-registration since the snapshot is in the
            # append-only range_log suffix: union it into the candidate
            # set (the geometric prune is lost only for the delta, whose
            # non-intersecting entries the overlap filter discards).
            # Refresh the probe IN PLACE so repeat serves in this window
            # take the version-match fast path.
            delta = store.range_log[probe.log_len:]
            if delta:
                seen_c = set(probe.candidates)
                probe.candidates = probe.candidates + tuple(
                    d for d in dict.fromkeys(delta) if d not in seen_c)
            probe.version = store.range_version
            probe.log_len = len(store.range_log)
        candidates = probe.candidates
        store.device_range_hits += 1
        served = []
        for txn_id in candidates:
            if not self._active_range_conflict(txn_id, before, kinds):
                continue
            ranges = store.range_commands.get(txn_id)
            if ranges is None:
                continue  # cleaned up since the snapshot: no conflict
            if is_range:
                overlap = ranges.intersection(owned)
            else:
                overlap = Ranges([r for r in ranges
                                  if any(r.contains(k) for k in owned)])
            if overlap.is_empty:
                continue
            if on_range_dep is not None:
                served.append(("r", overlap, txn_id))
            else:
                for key in (self._owned_cfk_keys(overlap) if is_range
                            else [k for k in owned if overlap.contains(k)]):
                    served.append(("k", key, txn_id))
        if store.verify:
            self._verify_range_arm(owned, is_range, before, kinds,
                                   on_range_dep is not None, served)
        for tag, a, txn_id in served:
            if tag == "r":
                on_range_dep(a, txn_id)
            else:
                fn(a, txn_id)

    def _verify_range_arm(self, owned, is_range, before, kinds,
                          has_range_sink, served) -> None:
        want = []
        super()._map_range_conflicts(
            owned, is_range, before, kinds,
            lambda k, t: want.append(("k", k, t)),
            (lambda o, t: want.append(("r", o, t)))
            if has_range_sink else None)

        def norm(items):
            return sorted(
                (tag, tuple((r.start, r.end) for r in a) if tag == "r"
                 else a.token, t) for tag, a, t in items)

        if norm(served) != norm(want):
            err = AssertionError(
                f"device range arm diverges from scalar at "
                f"(before={before!r}): device={norm(served)} "
                f"scalar={norm(want)}")
            try:
                self.store.agent.on_uncaught_exception(err)
            except Exception:
                pass
            raise err

    # ---------------------------------------------- recovery scans (keys) --
    def _recovery_servable(self, txn_id: TxnId, participants):
        """The precomputed recovery probe and the owned KEY list, when every
        queried key is covered and exactly at its snapshot version.  An
        empty key list (no CFK state inside the participants — collection
        skips such probes too) serves trivially, matching the deps arm."""
        store: DeviceCommandStore = self.store
        owned = self._owned_participants(participants)
        keys = (self._owned_cfk_keys(owned) if isinstance(owned, Ranges)
                else list(owned))
        if not keys:
            return _EMPTY_RECOVERY, []
        probe = store._precomputed_recovery.get(txn_id)
        if probe is None:
            return None, None
        for k in keys:
            cfk = store.cfks.get(k)
            v = cfk.version if cfk is not None else 0
            if k not in probe.key_set or v != probe.versions.get(k, 0):
                return None, None
        return probe, keys

    def _serve_recovery(self, which: str, txn_id: TxnId, participants,
                        scalar_fn):
        probe, keys = self._recovery_servable(txn_id, participants)
        if probe is None:
            self.store.device_recovery_misses += 1
            return None
        self.store.device_recovery_hits += 1
        keyed = getattr(probe, which)
        if self.store.verify:
            want: Dict[Key, List[TxnId]] = {}
            scalar_fn(want)
            got = {k: keyed[k] for k in keys if keyed.get(k)}
            want = {k: sorted(v) for k, v in want.items() if v}
            if got != want:
                err = AssertionError(
                    f"device recovery scan '{which}' diverges for {txn_id}: "
                    f"device={got} scalar={want}")
                try:
                    self.store.agent.on_uncaught_exception(err)
                except Exception:
                    pass
                raise err
        return {k: keyed[k] for k in keys if keyed.get(k)}

    def _decipher_fast_path_keys(self, txn_id: TxnId, participants):
        # the batched masks enumerate RAW candidates; the elision
        # classifier (CommandsForKey.omission_covers) is a host-side
        # post-step shared with the scalar path — including its third
        # verdict (unresolved covers the coordinator must await)
        def scalar_collect(out):
            for cfk in self._participant_cfks(participants):
                found = cfk.started_after_without_witnessing_ids(txn_id,
                                                                 raw=True)
                if found:
                    out.setdefault(cfk.key, []).extend(found)

        served_a = self._serve_recovery("rejects_a", txn_id, participants,
                                        scalar_collect)
        if served_a is None:
            return super()._decipher_fast_path_keys(txn_id, participants)

        def scalar_collect_b(out):
            for cfk in self._participant_cfks(participants):
                found = cfk.executes_after_without_witnessing_ids(txn_id,
                                                                 raw=True)
                if found:
                    out.setdefault(cfk.key, []).extend(found)

        served_b = self._serve_recovery("rejects_b", txn_id, participants,
                                        scalar_collect_b)
        if served_b is None:
            return super()._decipher_fast_path_keys(txn_id, participants)
        return self._classify_omission_maps((served_a, served_b), txn_id)

    def _earlier_committed_witness_keys(self, txn_id, participants,
                                        builder) -> None:
        def scalar_collect(out):
            for cfk in self._participant_cfks(participants):
                ids = cfk.stable_started_before_and_witnessed(txn_id)
                if ids:
                    out.setdefault(cfk.key, []).extend(ids)

        served = self._serve_recovery("witness", txn_id, participants,
                                      scalar_collect)
        if served is None:
            return super()._earlier_committed_witness_keys(
                txn_id, participants, builder)
        for k, ids in served.items():
            for t in ids:
                builder.add(k, t)

    def _earlier_accepted_no_witness_keys(self, txn_id, participants,
                                          builder) -> None:
        def scalar_collect(out):
            for cfk in self._participant_cfks(participants):
                ids = cfk.accepted_started_before_without_witnessing(txn_id)
                if ids:
                    out.setdefault(cfk.key, []).extend(ids)

        served = self._serve_recovery("no_witness", txn_id, participants,
                                      scalar_collect)
        if served is None:
            return super()._earlier_accepted_no_witness_keys(
                txn_id, participants, builder)
        for k, ids in served.items():
            for t in ids:
                builder.add(k, t)

    def _version_ok(self, key: Key, probe: _Probe,
                    exclude: Optional[TxnId]) -> bool:
        cfk = self.store.cfks.get(key)
        v = cfk.version if cfk is not None else 0
        snap = probe.versions.get(key, 0)
        if v == snap:
            return True
        # sole mutation since the snapshot = the querying txn's own
        # registration, which its scan excludes (deps_kernel `earlier` for
        # preaccept; commands.calculate_deps' dep != txn_id filter otherwise).
        # The committed view must be untouched: committing/invalidating the
        # querier moves the transitive-elision bound, which changes OTHER
        # entries' visibility — self-exclusion does not cover that.
        return (v == snap + 1 and exclude is not None
                and cfk is not None and cfk.last_mutator == exclude
                and cfk.committed_version
                == probe.committed_versions.get(key, 0))

    def _verify_against_scalar(self, owned, before, kinds, exclude,
                               probe: _Probe) -> None:
        got: Dict[Key, List[TxnId]] = {}

        def collect(k, t):
            if t != exclude:
                got.setdefault(k, []).append(t)

        # key tier only — the range arm has its own probe machinery and
        # verify pass (_map_range_conflicts / _verify_range_arm)
        for key in owned:
            cfk = self.store.cfks.get(key)
            if cfk is not None:
                cfk.map_reduce_active(before, kinds,
                                      lambda t, k=key: collect(k, t))
        for key in owned:
            want = sorted(got.get(key, []))
            served = [d for d in probe.keyed.get(key, ()) if d != exclude]
            if served != want:
                err = AssertionError(
                    f"device deps diverge from scalar at {key}: "
                    f"device={served} scalar={want}")
                # raise through the agent too: op-level failures become
                # FailureReplies (a routine nack), which must not mask a
                # broken equivalence contract in the burn
                try:
                    self.store.agent.on_uncaught_exception(err)
                except Exception:
                    pass
                raise err


class DeviceCommandStore(CommandStore):
    """CommandStore with flush-window batching onto the device tier.

    `_submit` defers operations; a zero-delay (or `flush_window_us`-delayed)
    scheduler event drains the window: one batched kernel call precomputes
    every declared deps probe, then the operations run serially.

    The `device_*` counters live in the node's metrics registry (obs/) —
    the attribute names below are read-through views (obs/views.MetricView)
    so the burn/measure harnesses and the `+=` call sites are unchanged.
    """

    device_hits = MetricView("accord_device_hits_total")
    device_misses = MetricView("accord_device_misses_total")
    device_batches = MetricView("accord_device_kernel_batches_total")
    device_batched_probes = MetricView("accord_device_batched_probes_total")
    device_max_batch = MetricView("accord_device_max_batch", kind="gauge")
    # flush-window accounting: every drained window, plus the
    # cross-transaction fusion the ingest pipeline exists to create
    device_flush_windows = MetricView("accord_device_flush_windows_total")
    device_cross_txn_windows = MetricView(
        "accord_device_cross_txn_windows_total")
    device_window_txn_max = MetricView("accord_device_window_txn_max",
                                       kind="gauge")
    device_recovery_hits = MetricView("accord_device_recovery_hits_total")
    device_recovery_misses = MetricView(
        "accord_device_recovery_misses_total")
    device_wave_batches = MetricView("accord_device_wave_batches_total")
    device_wave_planned = MetricView("accord_device_wave_planned_total")
    device_wave_executed = MetricView("accord_device_wave_executed_total")
    device_wave_max_depth = MetricView("accord_device_wave_max_depth",
                                       kind="gauge")
    device_range_hits = MetricView("accord_device_range_hits_total")
    device_range_misses = MetricView("accord_device_range_misses_total")
    device_range_batches = MetricView("accord_device_range_batches_total")
    device_range_candidates = MetricView(
        "accord_device_range_candidates_total")
    # compile-count hook: jit caches per argument-shape tuple, so the
    # first window at a NEW encoded shape pays an XLA compile — counting
    # distinct shapes counts compiles without touching jax internals
    device_compile_shapes = MetricView("accord_device_compile_shapes_total")

    def __init__(self, store_id: int, node, ranges, *,
                 flush_window_us: int = 0, verify: bool = False,
                 plan_waves: bool = True):
        super().__init__(store_id, node, ranges)
        self.flush_window_us = flush_window_us
        self.verify = verify
        self.plan_waves = plan_waves  # A/B toggle (measure_device.py)
        self._window: List[Tuple[PreLoadContext, object, object]] = []
        self._flush_scheduled = False
        # >0 while a batch envelope (messages/multi.MultiPreAccept) is
        # applying its parts: deliveries accumulate without scheduling a
        # flush, so the WHOLE envelope resolves as one fused probe window
        # regardless of flush_window_us (the ingest pipeline's contract)
        self._flush_hold = 0
        self._precomputed: Dict[Tuple[Timestamp, KindSet], _Probe] = {}
        self._precomputed_recovery: Dict[TxnId, _RecoveryProbe] = {}
        self._precomputed_ranges: Dict[Tuple[Timestamp, KindSet],
                                       _RangeProbe] = {}
        # (range_version, ids, intervals, dev_starts, dev_ends) — the
        # encoded range index, reused across windows until a mutation
        self._range_index_cache = None
        registry = getattr(getattr(node, "obs", None), "registry", None)
        if registry is None:  # bare-store harnesses without a full Node
            from accord_tpu.obs.registry import Registry
            registry = Registry()
        bind_metric_views(self, registry, store=store_id)
        # kernel-level profiler (obs/profiler.py): fenced per-kernel laps +
        # flush-window waterfall, sampled 1-in-N under ACCORD_PROFILE=N
        # (off by default; the always-on retrace ledger is a set lookup).
        # Fencing is the host pull each lap already ends with — the
        # profiler itself never imports jax.
        from accord_tpu.obs.profiler import profiler_from_env
        self.profiler = profiler_from_env(registry)
        self._window_opened = None  # wall stamp of the window's first submit
        # miss-cause breakdown for the deps arm (hit-rate diagnosis):
        # no_probe (nothing precomputed at this (before, kinds)), version
        # (gate tripped), key_cover (probe didn't cover a queried key)
        self.device_miss_causes = CounterDict(
            registry, "accord_device_miss_causes_total",
            ("no_probe", "version", "key_cover"), label="cause",
            store=store_id)
        self._h_window_txns = registry.histogram(
            "accord_device_window_txns", store=store_id)
        self._seen_shapes = set()  # encoded-shape buckets (compile count)
        # set when the device backend dies mid-run (e.g. the TPU tunnel
        # drops): the store keeps serving every scan through the scalar
        # path instead of crashing the node
        self.device_disabled = False
        # enable the range-registration delta log (local/store.py); the
        # flush boundary clears it together with the probes it serves
        self.range_log = []

    @classmethod
    def factory(cls, flush_window_us: int = 0, verify: bool = False,
                plan_waves: bool = True):
        return lambda i, node, ranges: cls(i, node, ranges,
                                           flush_window_us=flush_window_us,
                                           verify=verify,
                                           plan_waves=plan_waves)

    def _make_safe(self, context: PreLoadContext) -> SafeCommandStore:
        return DeviceSafeCommandStore(self, context)

    def _submit(self, context: PreLoadContext, fn, result) -> None:
        if self.device_disabled:
            # degraded store: no batched precompute will ever run, so skip
            # the dead flush-window deferral entirely
            super()._submit(context, fn, result)
            return
        if self.profiler.enabled and not self._window:
            import time as _time
            self._window_opened = _time.perf_counter()
        self._window.append((context, fn, result))
        if not self._flush_scheduled and self._flush_hold == 0:
            self._flush_scheduled = True
            if self.flush_window_us > 0:
                self.node.scheduler.once(self.flush_window_us / 1e6,
                                         self._flush)
            else:
                self.node.scheduler.now(self._flush)

    def _note_compile_shape(self, *shapes, kernel: str = "deps") -> None:
        """First sighting of an encoded-shape bucket == one XLA compile of
        the kernel at that shape (jit caches per shape tuple).  The same
        buckets key the profiler's retrace ledger."""
        self.profiler.note_retrace(kernel, shapes)
        if shapes not in self._seen_shapes:
            self._seen_shapes.add(shapes)
            self.device_compile_shapes += 1

    # ----------------------------------------------- envelope window pins --
    def hold_flush(self) -> None:
        """Pin the flush window open (batch envelope applying its parts)."""
        self._flush_hold += 1

    def release_flush(self) -> None:
        self._flush_hold -= 1
        if self._flush_hold == 0 and self._window \
                and not self._flush_scheduled:
            # flush the pinned accumulation now — the envelope already
            # bounded the window; adding the flush delay on top would tax
            # latency twice
            self._flush_scheduled = True
            self.node.scheduler.now(self._flush)

    def _flush(self) -> None:
        self._flush_scheduled = False
        if self._flush_hold > 0:
            # a pre-hold timer fired mid-envelope: defer — release_flush
            # reschedules with the full envelope accumulated
            return
        window, self._window = self._window, []
        if not window:
            return
        window_txns: Set[TxnId] = set()
        for context, _fn, _result in window:
            window_txns.update(context.txn_ids)
        self.device_flush_windows += 1
        self._h_window_txns.observe(len(window_txns))
        if len(window_txns) > 1:
            self.device_cross_txn_windows += 1
        self.device_window_txn_max = max(self.device_window_txn_max,
                                         len(window_txns))
        prof = self.profiler
        prof.window_begin(self._window_opened)
        self._window_opened = None
        plan = None
        if not self.device_disabled:
            try:
                self._precompute(window)
                self._precompute_recovery(window)
                self._precompute_ranges(window)
                if self.plan_waves:
                    plan = self._plan_waves(window)
            except Exception as exc:  # noqa: BLE001 — mid-run backend death
                if self.verify:
                    # equivalence-certification mode must not silently
                    # degrade to a scalar-only run that still reports OK:
                    # a kernel/encoder regression surfaces here
                    raise
                # a dying tunneled backend must not take the replica down:
                # disable the device tier for this store and serve every
                # scan through the scalar path from here on (recorded via
                # the agent so harnesses can assert on backend incidents)
                self.device_disabled = True
                self._precomputed = {}
                self._precomputed_recovery = {}
                self._precomputed_ranges = {}
                self.range_log = None  # no consumer remains; stop logging
                self.agent.on_handled_exception(exc)
        prof.window_end()
        if plan is not None:
            window = self._schedule_window(window, plan)
        try:
            for context, fn, result in window:
                super()._submit(context, fn, result)
        finally:
            self._precomputed = {}
            self._precomputed_recovery = {}
            self._precomputed_ranges = {}
            if self.range_log is not None:
                # probes are gone; rebase the delta log so it stays bounded
                self.range_log.clear()
            if plan is not None:
                self._account_wave_execution(plan)

    def _collect_deps_probes(self, window
                             ) -> List[Tuple[Timestamp, KindSet, List[Key]]]:
        probes: List[Tuple[Timestamp, KindSet, List[Key]]] = []
        seen: Set[Tuple[Timestamp, KindSet]] = set()
        for context, _fn, _result in window:
            for before, kinds, keys in context.deps_probes:
                if (before, kinds) in seen:
                    continue
                owned = self._snapshot_probe_keys(keys)
                if len(owned) == 0:
                    continue
                seen.add((before, kinds))
                probes.append((before, kinds, owned))
        return probes

    def _snapshot_probe_keys(self, keys) -> List[Key]:
        """The owned KEY list a probe covers, at snapshot time.  A Ranges
        probe (sync point / range txn) materializes to the CFK keys inside
        the ranges — its per-key tier is exactly that walk; the geometric
        range-command arm still goes to the stab tier.  A key born after
        this snapshot fails the serve-time cover gate and falls back to
        scalar."""
        owned = keys.slice(self.ranges) if not self.ranges.is_empty else keys
        if isinstance(owned, Ranges):
            return self.cfk_keys_in(owned)
        return list(owned)

    def _probe_snapshots(self, probes):
        touched = sorted({k for _, _, ks in probes for k in ks})
        cfks = [self.cfks[k] for k in touched if k in self.cfks]
        versions = {k: (self.cfks[k].version if k in self.cfks else 0)
                    for k in touched}
        committed_versions = {
            k: (self.cfks[k].committed_version if k in self.cfks else 0)
            for k in touched}
        return cfks, versions, committed_versions

    def _install_probes(self, probes, keyed, versions,
                        committed_versions) -> None:
        self.device_batches += 1
        self.device_batched_probes += len(probes)
        self.device_max_batch = max(self.device_max_batch, len(probes))
        for (before, kinds, ks), m in zip(probes, keyed):
            self._precomputed[(before, kinds)] = _Probe(
                before, kinds, m, set(ks), versions, committed_versions)

    def _precompute(self, window) -> None:
        self._precomputed = {}
        probes = self._collect_deps_probes(window)
        if not probes:
            return

        from accord_tpu.ops.deps_kernel import batched_active_deps
        from accord_tpu.ops.encode import BatchEncoder

        # each profiler lap ends at a host pull (np.asarray) — the pull IS
        # the fence, so "device" measures the kernel, not dispatch overlap
        t = self.profiler.begin()
        cfks, versions, committed_versions = self._probe_snapshots(probes)
        enc = BatchEncoder.for_probes(cfks, probes)
        s, b = enc.state, enc.dbatch
        t = self.profiler.lap(t, "deps_encode", stage="encode")
        self._note_compile_shape(s.entry_rank.shape, b.touches.shape)
        dep_mask, _count = batched_active_deps(
            s.entry_rank, s.entry_eat_rank, s.entry_key, s.entry_status,
            s.entry_kind, b.txn_rank, b.txn_witness_mask, b.touches)
        mask_host = np.asarray(dep_mask)
        t = self.profiler.lap(t, "deps_kernel", stage="device")
        keyed = enc.decode_key_deps(mask_host)
        self.profiler.lap(t, "deps_decode", stage="decode")
        self._install_probes(probes, keyed, versions, committed_versions)

    def _precompute_recovery(self, window) -> None:
        """Batch every declared recovery probe (BeginRecovery's four
        mapReduceFull predicates) into one kernel call."""
        self._precomputed_recovery = {}
        probes: List[Tuple[TxnId, List[Key]]] = []
        seen: Set[TxnId] = set()
        for context, _fn, _result in window:
            for txn_id, keys in context.recovery_probes:
                if txn_id in seen:
                    continue
                owned = self._snapshot_probe_keys(keys)
                if len(owned) == 0:
                    continue
                seen.add(txn_id)
                probes.append((txn_id, owned))
        if not probes:
            return

        import numpy as _np

        from accord_tpu.ops.recovery_kernel import (RecoveryEncoder,
                                                    batched_recovery_scans)

        t = self.profiler.begin()
        touched = sorted({k for _, ks in probes for k in ks})
        cfks = [self.cfks[k] for k in touched if k in self.cfks]
        versions = {k: (self.cfks[k].version if k in self.cfks else 0)
                    for k in touched}
        enc = RecoveryEncoder(cfks, probes)
        args = enc.args()
        t = self.profiler.lap(t, "recovery_encode", stage="encode")
        self._note_compile_shape(
            *(getattr(a, "shape", None) for a in args), kernel="recovery")
        ra, rb, cw, anw = batched_recovery_scans(*args)
        ra, rb = _np.asarray(ra), _np.asarray(rb)
        cw, anw = _np.asarray(cw), _np.asarray(anw)
        t = self.profiler.lap(t, "recovery_kernel", stage="device")
        self.device_batches += 1
        self.device_batched_probes += len(probes)
        for i, (txn_id, ks) in enumerate(probes):
            self._precomputed_recovery[txn_id] = _RecoveryProbe(
                txn_id, enc.decode_keyed(ra[i]), enc.decode_keyed(rb[i]),
                enc.decode_keyed(cw[i]), enc.decode_keyed(anw[i]),
                set(ks), versions)
        self.profiler.lap(t, "recovery_decode", stage="decode")

    def _precompute_ranges(self, window) -> None:
        """Stab the live range-command index with every declared probe's
        participants in one [Q, N] kernel call (ops/range_kernel.py; the
        reference's per-query CINTIA checkpoint walk, RangeDeps.java:63-120
        + SearchableRangeList.java:79, redesigned as a dense broadcast
        compare).  Key-domain participants stab as unit intervals
        [token, token+1); range-domain as their spans."""
        self._precomputed_ranges = {}
        if not self.range_commands:
            return
        probes = []
        seen: Set[Tuple[Timestamp, KindSet]] = set()
        for context, _fn, _result in window:
            for before, kinds, participants in context.deps_probes:
                if (before, kinds) in seen:
                    continue
                owned = participants.slice(self.ranges) \
                    if not self.ranges.is_empty else participants
                if isinstance(owned, Ranges):
                    if owned.is_empty:
                        continue
                    spans = [(r.start, r.end) for r in owned]
                    mode, owned_repr = "ranges", tuple(spans)
                else:
                    if len(owned) == 0:
                        continue
                    spans = [(k.token, k.token + 1) for k in owned]
                    mode, owned_repr = "keys", frozenset(
                        k.token for k in owned)
                seen.add((before, kinds))
                probes.append((before, kinds, mode, owned_repr, spans))
        if not probes:
            return

        import jax.numpy as jnp

        from accord_tpu.ops.encode import _pad_to
        from accord_tpu.ops.range_kernel import range_stab_mask

        # the encoded interval index is cached on range_version: a steady
        # workload over a rarely-mutated index re-uses the device-resident
        # bound arrays and pays only for the query side
        cache = self._range_index_cache
        if cache is not None and cache[0] == self.range_version:
            _, ids, intervals, dev_starts, dev_ends = cache
        else:
            ids = list(self.range_commands.keys())
            intervals = []
            for idx, t in enumerate(ids):
                for r in self.range_commands[t]:
                    intervals.append((r.start, r.end, idx))
            if not intervals:
                self._range_index_cache = None
                return
            n_pad = _pad_to(len(intervals), 128)
            starts = np.zeros(n_pad, np.int32)
            ends = np.zeros(n_pad, np.int32)  # empty [0,0) pads never match
            for i, (s, e, _idx) in enumerate(intervals):
                starts[i], ends[i] = s, e
            dev_starts = jnp.asarray(starts)
            dev_ends = jnp.asarray(ends)
            self._range_index_cache = (self.range_version, ids, intervals,
                                       dev_starts, dev_ends)
        if not intervals:
            return
        t = self.profiler.begin()
        all_spans = [sp for _, _, _, _, spans in probes for sp in spans]
        q_pad = _pad_to(len(all_spans), 128)
        qs = np.zeros(q_pad, np.int32)
        qe = np.zeros(q_pad, np.int32)
        for i, (s, e) in enumerate(all_spans):
            qs[i], qe[i] = s, e
        t = self.profiler.lap(t, "range_encode", stage="encode")
        self._note_compile_shape(dev_starts.shape, (q_pad,), kernel="range")
        mask = np.asarray(range_stab_mask(
            dev_starts, dev_ends, jnp.asarray(qs), jnp.asarray(qe)))
        t = self.profiler.lap(t, "range_kernel", stage="device")
        self.device_range_batches += 1
        version = self.range_version
        row = 0
        for before, kinds, mode, owned_repr, spans in probes:
            cand: Set[TxnId] = set()
            for _ in spans:
                for j in np.nonzero(mask[row][:len(intervals)])[0]:
                    cand.add(ids[intervals[j][2]])
                row += 1
            self.device_range_candidates += len(cand)
            self._precomputed_ranges[(before, kinds)] = _RangeProbe(
                before, kinds, mode, owned_repr, tuple(sorted(cand)),
                version, log_len=len(self.range_log))
        self.profiler.lap(t, "range_decode", stage="decode")

    # ------------------------------------------------ wavefront scheduling --
    def _plan_waves(self, window):
        """Plan the window's Apply order with the wavefront kernel.

        The scalar path resolves execution order one command at a time:
        each applied dependency walks its listeners and re-tests WaitingOn
        (reference Commands.maybeExecute :656 / NotifyWaitingOn :1011).
        When several Applies land in one flush window, the device instead
        computes the window's conflict DAG (ops.deps_kernel.in_batch_graph:
        shared-key ∧ earlier-executeAt ∧ witnesses, one MXU matmul) and
        Kahn-layers it (ops.wavefront.execution_waves); the window's
        Applies then run in wave order, so each one finds its in-window
        dependencies already applied and executes immediately instead of
        parking in WaitingOn and being re-driven by the listener cascade.

        Correctness NEVER depends on the plan: it only reorders message
        application (legal under the protocol's arbitrary-delivery model —
        the sim's nemeses reorder far more aggressively), and the scalar
        WaitingOn machinery still gates every transition.  The plan's
        *accuracy* is certified in verify mode: the device wave assignment
        is asserted equal to the host oracle (ops.wavefront.waves_oracle)
        on an identically-defined host-derived graph.

        Returns {txn_id: (wave, execute_at)} or None when the window holds
        fewer than two plannable Applies."""
        from accord_tpu.local.status import SaveStatus

        probes = []
        seen: Set[TxnId] = set()
        for context, _fn, _result in window:
            for txn_id, execute_at, keys in context.execute_probes:
                if txn_id in seen:
                    continue
                seen.add(txn_id)
                cmd = self.commands.get(txn_id)
                if cmd is not None and cmd.save_status >= SaveStatus.APPLYING:
                    continue  # redundant re-delivery: nothing to schedule
                owned = keys.slice(self.ranges) \
                    if not self.ranges.is_empty else keys
                if len(owned) == 0:
                    continue
                probes.append((txn_id, execute_at,
                               [k.token for k in owned]))
        if len(probes) < 2:
            return None

        import jax.numpy as jnp

        from accord_tpu.ops.deps_kernel import in_batch_graph
        from accord_tpu.ops.encode import _pad_to, witness_mask
        from accord_tpu.ops.wavefront import execution_waves

        t_prof = self.profiler.begin()
        n = len(probes)
        tokens = sorted({t for _, _, toks in probes for t in toks})
        tindex = {t: i for i, t in enumerate(tokens)}
        order = sorted(range(n), key=lambda i: probes[i][1])
        b = _pad_to(n, 128)
        kpad = _pad_to(len(tokens), 128)
        txn_rank = np.full(b, -1, np.int32)
        txn_wmask = np.zeros(b, np.int32)
        txn_kind = np.zeros(b, np.int32)
        touches = np.zeros((b, kpad), bool)
        for rank, i in enumerate(order):
            txn_id, _eat, toks = probes[i]
            txn_rank[i] = rank
            txn_wmask[i] = witness_mask(txn_id.kind)
            txn_kind[i] = int(txn_id.kind)
            for t in toks:
                touches[i, tindex[t]] = True
        t_prof = self.profiler.lap(t_prof, "wavefront_encode",
                                   stage="encode")
        self._note_compile_shape((b,), (b, kpad), kernel="wavefront")
        dep_bb = in_batch_graph(jnp.asarray(txn_rank),
                                jnp.asarray(txn_wmask),
                                jnp.asarray(txn_kind),
                                jnp.asarray(touches))
        waves = np.asarray(execution_waves(dep_bb))[:n]
        self.profiler.lap(t_prof, "wavefront_kernel", stage="device")
        if self.verify:
            self._verify_waves(probes, txn_rank, txn_wmask, txn_kind, waves)
        self.device_wave_batches += 1
        self.device_wave_planned += n
        self.device_wave_max_depth = max(self.device_wave_max_depth,
                                         int(waves.max()) + 1)
        return {probes[i][0]: (int(waves[i]), probes[i][1])
                for i in range(n)}

    def _verify_waves(self, probes, txn_rank, txn_wmask, txn_kind, waves):
        """Oracle-check the device wave assignment against the host
        layering of the identically-defined conflict graph."""
        from accord_tpu.ops.wavefront import waves_oracle

        n = len(probes)
        toksets = [set(toks) for _, _, toks in probes]
        rows = []
        for i in range(n):
            deps = [j for j in range(n)
                    if txn_rank[j] < txn_rank[i]
                    and (toksets[i] & toksets[j])
                    and ((txn_wmask[i] >> txn_kind[j]) & 1)]
            rows.append(deps)
        want = waves_oracle(rows)
        got = [int(w) for w in waves]
        if got != want:
            err = AssertionError(
                f"device waves diverge from host oracle: device={got} "
                f"host={want}")
            try:
                self.agent.on_uncaught_exception(err)
            except Exception:
                pass
            raise err

    def _schedule_window(self, window, plan):
        """Reorder the window: unplanned operations first in arrival order,
        then the planned Applies by (wave, executeAt, arrival)."""
        planned = []
        rest = []
        for idx, item in enumerate(window):
            context = item[0]
            key = None
            for txn_id, _eat, _keys in context.execute_probes:
                if txn_id in plan:
                    key = plan[txn_id]
                    break
            if key is None:
                rest.append(item)
            else:
                planned.append((key[0], key[1], idx, item))
        planned.sort(key=lambda x: (x[0], x[1], x[2]))
        return rest + [item for _, _, _, item in planned]

    def _account_wave_execution(self, plan) -> None:
        # plan membership implies the txn had NOT executed when the window
        # was planned (_plan_waves filters already-APPLYING re-deliveries),
        # so reaching APPLYING now means this window's schedule ran it
        from accord_tpu.local.status import SaveStatus
        for txn_id in plan:
            cmd = self.commands.get(txn_id)
            if cmd is not None \
                    and cmd.save_status >= SaveStatus.APPLYING:
                self.device_wave_executed += 1


class MeshDeviceCommandStore(DeviceCommandStore):
    """DeviceCommandStore whose batched deps precompute runs the
    mesh-sharded SPMD step over a `jax.sharding.Mesh`
    (ops/sharded.make_sharded_step: per-shard deps masks, psum'd counts,
    psum-of-matmuls conflict graph — the collective layout of the
    reference's CommandStores shard fan-out, CommandStores.java:78,
    mapped onto ICI instead of an executor pool).

    The protocol semantics are identical to DeviceCommandStore — same
    probe declarations, same serving, same version gates, same inline
    verification — only the kernel executing the window's deps scans is
    the multi-device step.  On a single-device backend it degrades to the
    parent's single-chip path."""

    def __init__(self, store_id: int, node, ranges, *,
                 flush_window_us: int = 0, verify: bool = False,
                 mesh=None, sharded_step=None, n_shards: int = 0):
        super().__init__(store_id, node, ranges,
                         flush_window_us=flush_window_us, verify=verify)
        self.mesh = mesh
        self._sharded_step = sharded_step
        self._mesh_shards = n_shards

    @classmethod
    def factory(cls, flush_window_us: int = 0, verify: bool = False,
                mesh=None):
        """One mesh + one compiled step shared by every store the factory
        creates (a per-store shard_map closure would recompile per store).
        With no mesh and a single-device backend, stores run the parent's
        single-chip path."""
        mesh, step, n_shards = _mesh_step_setup(mesh)
        return lambda i, node, ranges: cls(
            i, node, ranges, flush_window_us=flush_window_us, verify=verify,
            mesh=mesh, sharded_step=step, n_shards=n_shards)

    def _precompute(self, window) -> None:
        if self._sharded_step is None:
            return super()._precompute(window)
        self._precomputed = {}
        probes = self._collect_deps_probes(window)
        if not probes:
            return

        from accord_tpu.ops.encode import PAD
        from accord_tpu.ops.sharded import ShardedEncoder

        t = self.profiler.begin()
        cfks, versions, committed_versions = self._probe_snapshots(probes)
        # PAD-granular shape bucketing (not the encoder's default pad=8):
        # each distinct shape recompiles the shared jitted SPMD step
        enc = ShardedEncoder.for_probes(cfks, probes,
                                        n_shards=self._mesh_shards, pad=PAD)
        args = enc.args()
        t = self.profiler.lap(t, "sharded_encode", stage="encode")
        self._note_compile_shape(*(getattr(a, "shape", None)
                                   for a in args[:7]), kernel="sharded")
        dep_mask, _count = self._sharded_step(
            *args[:5], args[5], args[6], args[8])
        mask_host = np.asarray(dep_mask)
        t = self.profiler.lap(t, "sharded_kernel", stage="device")
        keyed = enc.decode_key_deps(mask_host)
        self.profiler.lap(t, "sharded_decode", stage="decode")
        self._install_probes(probes, keyed, versions, committed_versions)


def _mesh_step_setup(mesh):
    """Shared mesh + compiled SPMD step for a mesh-store factory: build the
    mesh from the visible devices when none is given (single-device backends
    get none, degrading stores to the single-chip path)."""
    import jax

    if mesh is None and len(jax.devices()) > 1:
        from jax.sharding import Mesh
        mesh = Mesh(np.array(jax.devices()), ("shard",))
    step = None
    n_shards = 0
    if mesh is not None:
        from accord_tpu.ops.sharded import make_sharded_deps_step
        step = make_sharded_deps_step(mesh)
        n_shards = mesh.devices.size
    return mesh, step, n_shards
