"""Debug command store: store-affinity and safe-store-leak detection.

Reference: accord/impl/InMemoryCommandStore.java:1191 (the Debug variant
asserting every access runs on the owning store's executor and detecting
SafeCommandStore references cached past their operation) and the
CommandStore.current() thread-affinity contract (CommandStore.java:228).

Python has no data-race detector to lean on (the reference treats this
variant as its TSan stand-in, SURVEY §5.2), so the Debug store checks the
two invariants that matter in a logically single-threaded-shard design:

* store affinity — every state access happens while THIS store's task is
  the one running (CommandStore.current() is the owner); a callback that
  closes over another shard's safe store trips it immediately;
* use-after-release — a SafeCommandStore reference cached beyond its task
  (the reference's "leaked safe store") fails on next use instead of
  silently mutating state outside the executor.
"""

from __future__ import annotations

from accord_tpu.local.store import (CommandStore, PreLoadContext,
                                    SafeCommandStore)
from accord_tpu.utils import invariants


class DebugSafeCommandStore(SafeCommandStore):
    def _check(self) -> None:
        invariants.check_state(
            not getattr(self, "released", False),
            "safe store for %s used after its task completed (leaked "
            "reference)", self.store)
        invariants.check_state(
            CommandStore.current() is self.store,
            "cross-store access: safe store of %s used while %s is current",
            self.store, CommandStore.current())

    # every state-touching entry point checks first
    def get(self, txn_id):
        self._check()
        return super().get(txn_id)

    def if_present(self, txn_id):
        self._check()
        return super().if_present(txn_id)

    def if_initialised(self, txn_id):
        self._check()
        return super().if_initialised(txn_id)

    def register(self, command, status):
        self._check()
        return super().register(command, status)

    def register_range_txn(self, command, ranges):
        self._check()
        return super().register_range_txn(command, ranges)

    def cfk(self, key):
        self._check()
        return super().cfk(key)

    def tfk(self, key):
        self._check()
        return super().tfk(key)

    def update_max_conflicts(self, participants, at):
        self._check()
        return super().update_max_conflicts(participants, at)


class DebugCommandStore(CommandStore):
    """Drop-in store variant for tests/burns: behaviourally identical, with
    the Debug assertions armed on every safe-store access."""

    def _make_safe(self, context: PreLoadContext) -> SafeCommandStore:
        return DebugSafeCommandStore(self, context)
