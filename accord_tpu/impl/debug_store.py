"""Debug command store: store-affinity and safe-store-leak detection.

Reference: accord/impl/InMemoryCommandStore.java:1191 (the Debug variant
asserting every access runs on the owning store's executor and detecting
SafeCommandStore references cached past their operation) and the
CommandStore.current() thread-affinity contract (CommandStore.java:228).

Python has no data-race detector to lean on (the reference treats this
variant as its TSan stand-in, SURVEY §5.2), so the Debug store checks the
two invariants that matter in a logically single-threaded-shard design:

* store affinity — every state access happens while THIS store's task is
  the one running (CommandStore.current() is the owner); a callback that
  closes over another shard's safe store trips it immediately;
* use-after-release — a SafeCommandStore reference cached beyond its task
  (the reference's "leaked safe store") fails on next use instead of
  silently mutating state outside the executor.
"""

from __future__ import annotations

from accord_tpu.local.store import (CommandStore, PreLoadContext,
                                    SafeCommandStore)
from accord_tpu.utils import invariants


class DebugSafeCommandStore(SafeCommandStore):
    """Every state access in SafeCommandStore — commands, CFKs, watermarks,
    the conflict-query/recovery scans, progress log, data store — goes
    through `self.store`, so intercepting that ONE attribute covers the
    whole surface (including entry points added later) without per-method
    wrappers."""

    def _check(self) -> None:
        invariants.check_state(
            not getattr(self, "released", False),
            "safe store for %s used after its task completed (leaked "
            "reference)", self._store)
        invariants.check_state(
            CommandStore.current() is self._store,
            "cross-store access: safe store of %s used while %s is current",
            self._store, CommandStore.current())

    @property
    def store(self) -> CommandStore:
        self._check()
        return self._store

    @store.setter
    def store(self, value: CommandStore) -> None:
        self._store = value


class DebugCommandStore(CommandStore):
    """Drop-in store variant for tests/burns: behaviourally identical, with
    the Debug assertions armed on every safe-store access."""

    def _make_safe(self, context: PreLoadContext) -> SafeCommandStore:
        return DebugSafeCommandStore(self, context)
