"""AbstractConfigurationService: the epoch-history topology feed.

Reference: accord/impl/AbstractConfigurationService.java — an ordered
per-epoch ledger (received -> acknowledged async stages), listener fan-out,
and gap-driven fetches: reporting epoch N when N-1 is unknown asks the
transport to fetch the missing predecessors, so listeners always observe
epochs in order. Transport-specific subclasses implement `fetch_topology`;
the sim's subclass resolves against the cluster's ledger directly.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from accord_tpu.api.spi import ConfigurationService, EpochReady
from accord_tpu.utils import invariants
from accord_tpu.utils.async_chains import AsyncResult


class EpochState:
    __slots__ = ("epoch", "received", "acknowledged", "topology")

    def __init__(self, epoch: int):
        self.epoch = epoch
        self.received: AsyncResult = AsyncResult()      # -> Topology
        self.acknowledged: AsyncResult = AsyncResult()  # -> None
        self.topology = None

    def __repr__(self):
        return f"EpochState({self.epoch})"


class EpochHistory:
    """Contiguous epoch ledger (AbstractEpochHistory)."""

    def __init__(self):
        self._epochs: List[EpochState] = []
        self.last_received = 0
        self.last_acknowledged = 0

    @property
    def min_epoch(self) -> int:
        return self._epochs[0].epoch if self._epochs else 0

    @property
    def max_epoch(self) -> int:
        return self._epochs[-1].epoch if self._epochs else 0

    def get_or_create(self, epoch: int) -> EpochState:
        invariants.check_argument(epoch > 0, "epochs start at 1")
        if not self._epochs:
            self._epochs.append(EpochState(epoch))
            return self._epochs[0]
        # extend below / above so the ledger stays contiguous
        while epoch < self._epochs[0].epoch:
            self._epochs.insert(0, EpochState(self._epochs[0].epoch - 1))
        while epoch > self._epochs[-1].epoch:
            self._epochs.append(EpochState(self._epochs[-1].epoch + 1))
        return self._epochs[epoch - self._epochs[0].epoch]

    def get(self, epoch: int) -> Optional[EpochState]:
        if not self._epochs \
                or not self._epochs[0].epoch <= epoch <= self._epochs[-1].epoch:
            return None
        return self._epochs[epoch - self._epochs[0].epoch]

    def truncate_until(self, epoch: int) -> None:
        """Shed epochs below `epoch` (topology GC)."""
        while self._epochs and self._epochs[0].epoch < epoch:
            self._epochs.pop(0)


class AbstractConfigurationService(ConfigurationService):
    # epoch-install gossip pacing: resend the install to topology members
    # that have not reported sync-complete, once per interval, for a
    # bounded number of rounds (partition-heal convergence without an
    # unbounded background chatter)
    GOSSIP_INTERVAL_S = 1.0
    GOSSIP_ROUNDS = 30

    def __init__(self, local_id: int):
        self.local_id = local_id
        self.epochs = EpochHistory()
        self.listeners: List = []
        self._fetching: Dict[int, bool] = {}
        self._delivered = 0  # highest epoch fanned out to listeners
        self.node = None          # set by attach_node
        self._specs: Dict[int, object] = {}  # epoch -> EpochInstall spec

    # ---------------------------------------------------------------- query --
    def current_topology(self):
        e = self.epochs.get(self.epochs.last_received)
        return e.topology if e is not None else None

    def get_topology_for_epoch(self, epoch: int):
        e = self.epochs.get(epoch)
        return e.topology if e is not None else None

    def register_listener(self, listener) -> None:
        self.listeners.append(listener)

    def attach_node(self, node) -> None:
        """Register a Node as listener AND wire its lazy epoch acquisition:
        Node.with_epoch on an epoch nobody has gossiped yet must actively
        fetch it (reference Node.withEpoch ->
        ConfigurationService.fetchTopologyForEpoch) — without the hook, an
        epoch-extension round or a message gated on a future epoch waits
        forever on gossip that may be lost."""
        self.register_listener(node)
        node.topology.set_fetch_hook(self.fetch_topology_for_epoch)
        self.node = node
        node.config_service = self

    # ---------------------------------------------------------- admin plane --
    def spec_for(self, epoch: int):
        """The EpochInstall spec this service witnessed for `epoch` (serves
        TopologyFetchReq gap fetches), or None."""
        return self._specs.get(epoch)

    def remember_spec(self, install) -> None:
        """Record an install spec without (re)applying it — used for the
        startup epoch, which is built locally rather than received."""
        self._specs.setdefault(install.epoch, install)

    def on_epoch_install(self, install, from_id: int) -> bool:
        """One EpochInstall witnessed (admin frame, gossip, or journal
        replay): dedupe against the ledger, apply through report_topology's
        in-order delivery, and gossip onward so a single admin contact
        converges the whole membership.  Returns False on a duplicate."""
        epoch = install.epoch
        if epoch in self._specs:
            return False
        self._specs[epoch] = install
        if install.peers:
            self.install_peers(install.peers)
        # decoded installs are __new__ + setattr (host/wire.py), so frames
        # from pre-geo senders simply lack the attribute
        if getattr(install, "geo", None):
            self.install_geo(install.geo)
        node = self.node
        if node is not None:
            node.obs.flight.record("epoch_install", None, (epoch, from_id))
        self.report_topology(install.build_topology())
        if node is not None and not getattr(node, "replaying", False):
            self._gossip_install(install, self.GOSSIP_ROUNDS)
        return True

    def install_peers(self, peers) -> None:
        """Transport hook: learn addresses for nodes joining in an installed
        epoch (the TCP host merges them into its peer table)."""

    def install_geo(self, geo) -> None:
        """Transport hook: a geo placement profile arrived with an epoch
        install (`GeoProfile.to_wire()` form); the TCP host rebuilds its
        egress delay shim from it."""

    def _gossip_install(self, install, rounds: int) -> None:
        node = self.node
        topology = self.get_topology_for_epoch(install.epoch)
        if node is None or topology is None:
            return
        behind = [to for to in sorted(topology.nodes())
                  if to != node.id
                  and not node.topology.epoch_acked_by(install.epoch, to)]
        for to in behind:
            node.send(to, install)
        if not behind or rounds <= 0:
            return
        node.scheduler.once(
            self.GOSSIP_INTERVAL_S,
            lambda: self._gossip_install(install, rounds - 1))

    # ----------------------------------------------------------------- feed --
    def report_topology(self, topology, start_sync: bool = True) -> None:
        """Record an epoch's topology; listeners observe epochs STRICTLY in
        order — an epoch arriving above a gap is buffered in the ledger, the
        missing predecessors are fetched, and delivery resumes once the
        prefix is contiguous (AbstractConfigurationService.reportTopology)."""
        epoch = topology.epoch
        self._fetching.pop(epoch, None)
        state = self.epochs.get_or_create(epoch)
        if state.topology is not None:
            return  # duplicate report
        state.topology = topology
        self.epochs.last_received = max(self.epochs.last_received, epoch)
        state.received.try_success(topology)
        self._deliver_contiguous(start_sync)

    def _deliver_contiguous(self, start_sync: bool) -> None:
        while True:
            nxt = (self._delivered + 1 if self._delivered
                   else self.epochs.min_epoch)
            state = self.epochs.get(nxt)
            if state is None:
                return
            if state.topology is None:
                # a gap: acquire it, delivery resumes when it reports
                self.fetch_topology_for_epoch(nxt)
                return
            self._delivered = nxt
            for listener in self.listeners:
                listener.on_topology_update(state.topology,
                                            start_sync=start_sync)

    def acknowledge_epoch(self, ready: EpochReady,
                          start_sync: bool = True) -> None:
        state = self.epochs.get_or_create(ready.epoch)
        self.epochs.last_acknowledged = max(self.epochs.last_acknowledged,
                                            ready.epoch)
        state.acknowledged.try_success(None)

    def fetch_topology_for_epoch(self, epoch: int) -> None:
        if self.get_topology_for_epoch(epoch) is not None \
                or self._fetching.get(epoch):
            return
        self._fetching[epoch] = True
        self.fetch_topology(epoch)

    # ------------------------------------------------------------ transport --
    def fetch_topology(self, epoch: int) -> None:
        """Transport hook: acquire `epoch` and call report_topology."""
        raise NotImplementedError


class DirectConfigService(AbstractConfigurationService):
    """Sim/host service: fetches resolve against a shared topology ledger
    (the cluster's, or the deterministically derived static topology)."""

    def __init__(self, local_id: int, lookup=None):
        super().__init__(local_id)
        self._lookup = lookup  # epoch -> Topology | None

    def fetch_topology(self, epoch: int) -> None:
        if self._lookup is None:
            self._fetching.pop(epoch, None)
            return
        topology = self._lookup(epoch)
        if topology is None:
            # not available yet: clear the in-flight flag so a later
            # attempt can retry (a stuck flag would suppress the fetch
            # forever and leave the gap unhealed)
            self._fetching.pop(epoch, None)
            return
        self.report_topology(topology)


class LedgerConfigService(AbstractConfigurationService):
    """Live-host service: no shared ledger exists, so epoch gaps are fetched
    from peers over the transport (TopologyFetchReq against any member of
    the newest topology we know)."""

    FETCH_TIMEOUT_S = 2.0

    def __init__(self, local_id: int, peers_hook=None, geo_hook=None):
        super().__init__(local_id)
        self._peers_hook = peers_hook
        self._geo_hook = geo_hook
        self._fetch_rr = 0  # round-robin cursor over candidate sources

    def install_peers(self, peers) -> None:
        if self._peers_hook is not None:
            self._peers_hook(peers)

    def install_geo(self, geo) -> None:
        if self._geo_hook is not None:
            self._geo_hook(geo)

    def fetch_topology(self, epoch: int) -> None:
        spec = self._specs.get(epoch)
        if spec is not None:
            self._fetching.pop(epoch, None)
            self.report_topology(spec.build_topology())
            return
        node = self.node
        current = self.current_topology()
        if node is None or current is None:
            self._fetching.pop(epoch, None)
            return
        candidates = [n for n in sorted(current.nodes()) if n != node.id]
        if not candidates:
            self._fetching.pop(epoch, None)
            return
        from accord_tpu.messages.admin import TopologyFetchOk, TopologyFetchReq
        from accord_tpu.messages.base import FunctionCallback
        to = candidates[self._fetch_rr % len(candidates)]
        self._fetch_rr += 1

        def on_ok(from_id, reply):
            self._fetching.pop(epoch, None)
            if isinstance(reply, TopologyFetchOk):
                # deliver through node.receive so the install is JOURNALED:
                # an epoch learned only via fetch must still survive a crash
                node.receive(reply.install, from_id, None)

        def on_fail(from_id, failure):
            # clear the in-flight flag; the 1 Hz epoch-fetch chain retries
            self._fetching.pop(epoch, None)

        node.send(to, TopologyFetchReq(epoch),
                  callback=FunctionCallback(on_ok, on_fail),
                  timeout_s=self.FETCH_TIMEOUT_S)
