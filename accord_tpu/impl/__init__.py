"""Reference SPI implementations (reference: accord/impl — SURVEY.md §2.7)."""
