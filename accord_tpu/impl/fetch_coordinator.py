"""FetchCoordinator: the ranged bootstrap-fetch engine behind DataStore.fetch.

Reference: accord/impl/AbstractFetchCoordinator.java driving FETCH_DATA_REQ,
against the api/DataStore.java:39-113 callback contract — per-range
progress (`FetchRanges.starting/fetched/fail`), source confirmation with an
optional max-applied bound (`StartingRangeFetch.started(maxApplied)`),
cancellation tokens (`AbortFetch`), and a `FetchResult` future that can
abort sub-ranges that stopped mattering (e.g. the topology moved them away
mid-bootstrap).

Shape of the protocol here: one FetchSnapshot request per (source, sub-range);
the source replies after the fence ExclusiveSyncPoint applied locally, with a
snapshot and its max applied executeAt for the covered keys.  Failed or
partial sub-ranges fail over to the next replica of their shard; when every
replica of a shard has been tried unsuccessfully the sub-range is reported
via `FetchRanges.fail` and the attempt's future fails (the caller — Bootstrap
— schedules a fresh attempt, reference Agent.onFailedBootstrap)."""

from __future__ import annotations

from typing import Dict, Optional, Set, Tuple

from accord_tpu.api.spi import DataStore
from accord_tpu.messages.base import Callback
from accord_tpu.messages.epoch import FetchSnapshot, FetchSnapshotOk
from accord_tpu.primitives.keys import Ranges


class _Starting:
    """StartingRangeFetch token (DataStore.java:41-61): created when we
    contact a source; `started(max_applied)` hands back an abort handle once
    the source confirmed its snapshot.  Forwards to the caller's own token
    (the return of FetchRanges.starting) so custom FetchRanges
    implementations observe per-source confirmation too."""

    __slots__ = ("coordinator", "ranges", "source", "aborted", "caller_token")

    def __init__(self, coordinator: "FetchCoordinator", ranges: Ranges,
                 source: int, caller_token=None):
        self.coordinator = coordinator
        self.ranges = ranges
        self.source = source
        self.aborted = False
        self.caller_token = caller_token

    def started(self, max_applied=None) -> "_Starting":
        if max_applied is not None:
            self.coordinator._observe_max_applied(max_applied)
        if self.caller_token is not None:
            self.caller_token.started(max_applied)
        return self  # the AbortFetch handle

    def cancel(self) -> None:
        """Abort before any data moved."""
        self.aborted = True
        if self.caller_token is not None:
            self.caller_token.cancel()

    def abort(self) -> None:
        """Abort after data may have moved (AbortFetch.abort)."""
        self.aborted = True
        if self.caller_token is not None \
                and hasattr(self.caller_token, "abort"):
            self.caller_token.abort()


class FetchCoordinator(Callback):
    def __init__(self, node, ranges: Ranges, sync_point, fetch_ranges,
                 data_store, timeout_s: Optional[float] = None):
        self.node = node
        self.ranges = ranges
        self.sync_point = sync_point
        self.fetch_ranges = fetch_ranges  # DataStore.FetchRanges callbacks
        self.data_store = data_store
        # per-source snapshot-fetch timeout (ACCORD_BOOTSTRAP_TIMEOUT_US via
        # LocalConfig): a wedged source fails over to the next replica
        # instead of stalling the whole attempt
        self.timeout_s = (timeout_s if timeout_s is not None
                          else node.config.bootstrap_fetch_timeout_s)
        self.result = DataStore.FetchResult()
        self.result.abort_hook = self.abort
        self.covered = Ranges.EMPTY
        self.failed = Ranges.EMPTY
        self.aborted = Ranges.EMPTY
        self.max_applied = None
        # source -> (requested sub-range, StartingRangeFetch token)
        self.inflight: Dict[int, Tuple[Ranges, _Starting]] = {}
        self.tried: Set[Tuple[int, object]] = set()
        self.done = False

    # ------------------------------------------------------------- driving --
    def start(self) -> "FetchCoordinator":
        self._fetch_missing()
        return self

    def _missing(self) -> Ranges:
        out = self.ranges.subtract(self.covered).subtract(self.aborted)
        return out.subtract(self.failed)

    def _fetch_missing(self) -> None:
        if self.done:
            return
        missing = self._missing()
        if missing.is_empty:
            self._maybe_finish()
            return
        topology = self.node.topology.for_epoch(self.sync_point.txn_id.epoch)
        requested = False
        for shard in topology.for_selection(missing).shards:
            want = Ranges([shard.range]).slice(missing)
            want = want.subtract(self._inflight_ranges())
            if want.is_empty:
                continue
            if not any(n != self.node.id for n in shard.nodes):
                # we are the only replica: nothing to copy for this shard
                self.covered = self.covered.union(want)
                self.fetch_ranges.fetched(want)
                continue
            source = self._pick_source(shard)
            if source is None:
                if any(n != self.node.id and n in self.inflight
                       for n in shard.nodes):
                    # replicas merely busy serving other sub-ranges: revisit
                    # when an in-flight request settles (on_success/failure
                    # re-run _fetch_missing) — NOT a permanent failure
                    continue
                # every replica tried for this shard: report failure upward;
                # the caller schedules a fresh attempt
                self.failed = self.failed.union(want)
                self.fetch_ranges.fail(
                    want, TimeoutError(f"all sources tried for {want}"))
                continue
            requested = True
            token = _Starting(self, want, source,
                              self.fetch_ranges.starting(want))
            self.inflight[source] = (want, token)
            self.node.send(source,
                           FetchSnapshot(self.sync_point.txn_id, want),
                           callback=self, timeout_s=self.timeout_s)
        if not requested and not self.inflight:
            self._maybe_finish()

    def _inflight_ranges(self) -> Ranges:
        out = Ranges.EMPTY
        for want, _tok in self.inflight.values():
            out = out.union(want)
        return out

    def _pick_source(self, shard) -> Optional[int]:
        # draining peers (scale-in, messages/admin.DrainBegin) are last
        # resort: prefer replicas that will still own the data tomorrow,
        # but a drainer beats failing the sub-range outright
        draining = getattr(self.node, "draining_peers", ())
        candidates = [n for n in shard.nodes
                      if n != self.node.id and n not in self.inflight
                      and (n, shard.range.start) not in self.tried]
        for pool in (True, False):
            for n in candidates:
                if (n not in draining) is pool:
                    self.tried.add((n, shard.range.start))
                    return n
        return None

    def _observe_max_applied(self, max_applied) -> None:
        if self.max_applied is None or max_applied > self.max_applied:
            self.max_applied = max_applied

    # ------------------------------------------------------------- replies --
    def on_success(self, from_id: int, reply) -> None:
        if self.done:
            return
        want, token = self.inflight.pop(from_id, (None, None))
        if isinstance(reply, FetchSnapshotOk) and token is not None \
                and not token.aborted:
            token.started(reply.max_applied)
            self.data_store.install_snapshot(reply.snapshot)
            # never credit/report sub-ranges aborted while in flight — the
            # caller dropped them and must not see them bootstrapped
            got = reply.ranges.subtract(self.aborted)
            self.covered = self.covered.union(got)
            if not got.is_empty:
                self.fetch_ranges.fetched(got)
        elif token is not None and not token.aborted:
            # nack (fence not applied there yet, or not a replica): no data
            # moved — cancel so caller-side token tracking closes out
            token.cancel()
        self._fetch_missing()

    def on_failure(self, from_id: int, failure: BaseException) -> None:
        if self.done:
            return
        want, token = self.inflight.pop(from_id, (None, None))
        if token is not None:
            token.cancel()
        self._fetch_missing()

    # -------------------------------------------------------------- finish --
    def abort(self, ranges: Ranges) -> None:
        """FetchResult.abort(ranges): these ranges stopped mattering (e.g.
        moved away by a newer topology) — drop them from the attempt and
        abort any in-flight source whose request is now fully irrelevant."""
        if self.done:
            return
        self.aborted = self.aborted.union(ranges)
        for source, (want, token) in list(self.inflight.items()):
            if want.subtract(self.aborted).is_empty:
                token.abort()
                self.inflight.pop(source, None)
        self._fetch_missing()

    def _maybe_finish(self) -> None:
        if self.done or self.inflight:
            return
        if not self._missing().is_empty:
            return
        self.done = True
        self.result.max_applied = self.max_applied
        if not self.failed.is_empty:
            self.result.try_failure(
                TimeoutError(f"fetch failed for {self.failed}"))
        else:
            self.result.try_success(self.covered)
