"""Out-of-band replica corruption: the audit tentpole's nemesis arm.

Silently mutates ONE replica's decided command state — no message, no
journal record, no flight event of the mutation itself — modelling the
failures the live auditor (local/audit.py) exists to catch online: a bad
replay, a codec bug, bit rot, an operator fat-finger.  The mutation
targets a command inside the NEGOTIATED audit window (below every
replica's universal-durable floor, above every bootstrap fence) so a
subsequent digest round is guaranteed to cover it.
"""

from __future__ import annotations

from typing import Optional

from accord_tpu.local.audit import entry_class, node_floors, _audit_scope, \
    _in_ranges
from accord_tpu.local.status import SaveStatus
from accord_tpu.primitives.keys import Ranges
from accord_tpu.primitives.timestamp import Timestamp


def corrupt_below_universal(cluster, node_id: int,
                            flip_invalidated: bool = False
                            ) -> Optional[object]:
    """Mutate one committed command on `node_id` that lies inside the
    cluster-negotiated audit window of some shard the node replicates:
    bump its executeAt hlc (default), or flip it to INVALIDATED.  Returns
    the corrupted TxnId, or None when no command is eligible yet (durable
    bounds not advanced far enough — retry after the next durability
    round)."""
    node = cluster.nodes[node_id]
    topo = node.topology.current()
    for shard in topo.shards:
        if node_id not in shard.nodes:
            continue
        ranges = Ranges([shard.range])
        # the negotiated window across the shard's LIVE replicas — what a
        # digest round would converge to
        lo = hi = None
        for rid in shard.nodes:
            if rid in cluster.dead:
                continue
            rlo, rhi = node_floors(cluster.nodes[rid], ranges)
            lo = rlo if lo is None else max(lo, rlo)
            hi = rhi if hi is None else min(hi, rhi)
        if lo is None or not (lo < hi):
            continue
        for store in node.command_stores.all():
            for txn_id, cmd in store.commands.items():
                if txn_id < lo or not (txn_id < hi):
                    continue
                ec = entry_class(cmd)
                if ec is None or ec[0] != "committed":
                    continue
                if not _in_ranges(_audit_scope(cmd), ranges):
                    continue
                if flip_invalidated:
                    # direct assignment, bypassing set_status: silent
                    # corruption must not announce itself on the flight
                    # ring — the auditor has to find it cold
                    cmd.save_status = SaveStatus.INVALIDATED
                else:
                    at = cmd.execute_at
                    cmd.execute_at = Timestamp(at.epoch, at.hlc + 1,
                                               at.flags, at.node)
                return txn_id
    return None
