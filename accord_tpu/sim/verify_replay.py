"""Second, independent history checker: witness construction + model replay.

The reference composes its own strict-serializability verifier with Elle
(jepsen's checker) so two unrelated algorithms must both pass
(test verify/CompositeVerifier, ElleVerifier.java:47).  This module is the
counterpart second algorithm: instead of testing the constraint graph for
cycles (sim/verify.py), it CONSTRUCTS an explicit serial witness order and
replays it against a model key-value store, validating every observation
against the model state at its position:

  1. phantom writers are synthesised for committed-but-unobserved appends
     (client-nacked transactions that actually won — their values appear in
     the final histories with no observation);
  2. ordering constraints are derived afresh — per-key final append order
     (ww), read-prefix placement (wr/rw), and real-time precedence;
  3. a topological order over them is the candidate witness; failure to
     find one is a serialization violation;
  4. the witness is replayed serially: each transaction's reads must equal
     the model state EXACTLY (the workload reads whole registers) and its
     appends are applied; the end state must equal the final histories.

Step 4 is the independence payoff: even if an edge rule in either checker
is subtly wrong, a wrong witness cannot replay cleanly.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Sequence, Tuple

from accord_tpu.sim.verify import (ForensicsMixin, Observation, Violation,
                                   real_time_edges)


class _Phantom:
    """Synthesised observation for an unobserved committed append."""

    __slots__ = ("token", "value")

    def __init__(self, token: int, value: int):
        self.token = token
        self.value = value

    def __repr__(self):
        return f"Phantom({self.token}={self.value})"


class WitnessReplayVerifier(ForensicsMixin):
    """Same observe/verify surface as StrictSerializabilityVerifier."""

    def __init__(self):
        self.observations: List[Observation] = []

    def observe(self, obs: Observation) -> None:
        self.observations.append(obs)

    # ------------------------------------------------------------ verify --
    def verify(self, final_histories: Dict[int, Sequence[int]]) -> None:
        obs = self.observations
        n = len(obs)
        # (token, value) -> final position; duplicates are caught by the
        # primary checker, but re-assert (independence)
        pos: Dict[Tuple[int, int], int] = {}
        for token, hist in final_histories.items():
            for i, v in enumerate(hist):
                if (token, v) in pos:
                    raise Violation(f"duplicate {v} in key {token}")
                pos[(token, v)] = i

        # writers per (token, position): observed index or phantom
        writer: Dict[Tuple[int, int], int] = {}
        for i, o in enumerate(obs):
            for token, value in o.appends.items():
                p = pos.get((token, value))
                if p is None:
                    raise Violation(
                        f"lost append {value} to key {token} by {o}")
                if (token, p) in writer:
                    raise Violation(f"key {token} pos {p} written twice")
                writer[(token, p)] = i
        phantoms: List[_Phantom] = []
        for token, hist in final_histories.items():
            for p in range(len(hist)):
                if (token, p) not in writer:
                    writer[(token, p)] = n + len(phantoms)
                    phantoms.append(_Phantom(token, hist[p]))
        total = n + len(phantoms)

        # -- constraints (fresh derivation) --
        succ: List[set] = [set() for _ in range(total)]
        indeg = [0] * total

        def edge(a: int, b: int) -> None:
            if a != b and b not in succ[a]:
                succ[a].add(b)
                indeg[b] += 1

        for token, hist in final_histories.items():
            for p in range(1, len(hist)):
                edge(writer[(token, p - 1)], writer[(token, p)])
        for i, o in enumerate(obs):
            for token, read in o.reads.items():
                hist = tuple(final_histories.get(token, ()))
                if tuple(read) != hist[:len(read)]:
                    raise Violation(
                        f"read {read} of key {token} is not a prefix of "
                        f"{hist} ({o})")
                if read:
                    edge(writer[(token, len(read) - 1)], i)  # wr
                if len(read) < len(hist):
                    edge(i, writer[(token, len(read))])      # rw
        real_time_edges(obs, edge)

        # -- witness construction (deterministic smallest-index-first
        #    topological order via a heap: O(E log V)) --
        ready = [i for i in range(total) if indeg[i] == 0]
        heapq.heapify(ready)
        witness: List[int] = []
        while ready:
            a = heapq.heappop(ready)
            witness.append(a)
            for b in succ[a]:
                indeg[b] -= 1
                if indeg[b] == 0:
                    heapq.heappush(ready, b)
        if len(witness) != total:
            stuck = [obs[i].txn_desc if i < n else phantoms[i - n]
                     for i in range(total) if indeg[i] > 0]
            raise self._violation(
                f"no serial witness exists; cyclic constraints around "
                f"{stuck[:10]}{'...' if len(stuck) > 10 else ''}",
                txn_descs=[d for d in stuck[:10] if isinstance(d, str)])

        # -- model replay --
        state: Dict[int, List[int]] = {}
        for idx in witness:
            if idx >= n:
                ph = phantoms[idx - n]
                state.setdefault(ph.token, []).append(ph.value)
                continue
            o = obs[idx]
            for token, read in o.reads.items():
                got = tuple(state.get(token, ()))
                if tuple(read) != got:
                    # with forensics attached the raw model-state dump is
                    # superseded by the stitched flight timeline, which
                    # leads with the first diverging cross-replica event
                    raise self._violation(
                        f"witness replay mismatch: {o} read {read} of key "
                        f"{token} but the model held {got}",
                        txn_descs=[o.txn_desc],
                        brief=(f"witness replay mismatch: {o.txn_desc} "
                               f"read key {token} diverges from the serial "
                               f"witness"))
            for token, value in o.appends.items():
                state.setdefault(token, []).append(value)
        for token, hist in final_histories.items():
            if tuple(state.get(token, ())) != tuple(hist):
                raise self._violation(
                    f"witness end-state mismatch on key {token}: model "
                    f"{state.get(token)} vs final {tuple(hist)}")


class CompositeVerifier:
    """Run every verifier over the same observations (the reference's
    CompositeVerifier wrapping its own checker + Elle)."""

    def __init__(self, *verifiers):
        self.verifiers = verifiers

    def observe(self, obs: Observation) -> None:
        for v in self.verifiers:
            v.observe(obs)

    def attach_forensics(self, fn) -> None:
        """Propagate the flight-timeline hook to every member checker
        that supports it (sim/verify.ForensicsMixin)."""
        for v in self.verifiers:
            if hasattr(v, "attach_forensics"):
                v.attach_forensics(fn)

    def verify(self, final_histories: Dict[int, Sequence[int]]) -> None:
        for v in self.verifiers:
            v.verify(final_histories)

    @property
    def observations(self):
        """The shared observation stream (every member sees the same one);
        exported by the external-Elle harness (sim/elle_export.py)."""
        return self.verifiers[0].observations


def full_verifier(witness_replay: bool = True) -> CompositeVerifier:
    """THE checker roster, in one place so no call site can drift to a
    weaker oracle: constraint-graph cycle test, witness construction +
    model replay (optional — the black-box host paths skip it), and the
    ported Elle list-append analysis."""
    from accord_tpu.sim.elle import ElleListAppendChecker
    from accord_tpu.sim.verify import StrictSerializabilityVerifier
    vs = [StrictSerializabilityVerifier()]
    if witness_replay:
        vs.append(WitnessReplayVerifier())
    vs.append(ElleListAppendChecker())
    return CompositeVerifier(*vs)
