"""Scheduler SPI implementation over the simulated queue (reference: the burn
Cluster implements accord.api.Scheduler — Cluster.java:102)."""

from __future__ import annotations

from typing import Callable

from accord_tpu.api.spi import Scheduler
from accord_tpu.sim.queue import PendingQueue


class SimScheduler(Scheduler):
    def __init__(self, queue: PendingQueue):
        self.queue = queue

    def once(self, delay_s: float, fn: Callable[[], None]):
        return self.queue.add(int(delay_s * 1e6), fn)

    def recurring(self, delay_s: float, fn: Callable[[], None]):
        return self.queue.add_recurring(int(delay_s * 1e6), fn)

    def now(self, fn: Callable[[], None]) -> None:
        self.queue.add(0, fn)

    def now_s(self) -> float:
        return self.queue.clock.now_s()
