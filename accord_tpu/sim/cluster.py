"""SimCluster: a whole Accord cluster in one deterministic event loop.

Reference: the burn-test cluster (accord-core test impl/basic/Cluster.java:102,
run loop :277-410): every node's executors, timers and deliveries share one
virtual-time queue; the loop is `while processPending()`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from accord_tpu.api.spi import Agent, EventsListener
from accord_tpu.impl.config_service import DirectConfigService
from accord_tpu.impl.list_store import ListStore
from accord_tpu.local.node import Node
from accord_tpu.primitives.keys import Range, Ranges
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.network import NodeSink, SimNetwork
from accord_tpu.sim.queue import PendingQueue
from accord_tpu.sim.scheduler import SimScheduler
from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topology import Topology
from accord_tpu.utils.random_source import RandomSource


class SimAgent(Agent):
    def __init__(self, cluster: "SimCluster", node_id: int):
        self.cluster = cluster
        self.node_id = node_id
        self.failures: List[BaseException] = []

    def on_uncaught_exception(self, failure: BaseException) -> None:
        self.failures.append(failure)
        self.cluster.queue.fail(failure)

    def on_handled_exception(self, failure: BaseException) -> None:
        # recorded (so harnesses can assert on incidents like a mid-run
        # device-backend death) but NOT fatal to the simulation
        self.failures.append(failure)

    def pre_accept_timeout(self) -> float:
        return 1.0  # virtual second

    def empty_txn(self, kind: TxnKind, keys_or_ranges) -> Txn:
        return Txn(kind, keys_or_ranges)


class DriftingClock:
    """Per-node wall clock: the shared virtual clock plus a bounded random
    walk (reference BurnTest.java:330-340 — per-node drifting clocks with
    frequent small jumps and occasional large ones, FrequentLargeRange).
    The HLC max-folds regressions away (Node.unique_now), so drift exercises
    timestamp ordering and preaccept-expiry paths without breaking
    monotonicity."""

    def __init__(self, clock, random: RandomSource, small_us: int = 2_000,
                 large_us: int = 10_000, bound_us: int = 50_000):
        self.clock = clock
        self.random = random
        self.small_us = small_us
        self.large_us = large_us
        self.bound_us = bound_us
        self.offset = 0

    def now_us(self) -> int:
        r = self.random
        step = (r.next_int(-self.large_us, self.large_us)
                if r.next_float() < 0.1
                else r.next_int(-self.small_us, self.small_us))
        self.offset = max(-self.bound_us,
                          min(self.bound_us, self.offset + step))
        return max(0, self.clock.now_us + self.offset)


class SimCluster:
    """N simulated nodes over a token-range topology."""

    def __init__(self, n_nodes: int = 3, seed: int = 0, token_span: int = 1000,
                 n_shards: int = 2, rf: int = None, num_command_stores: int = 1,
                 progress_log_factory: Optional[Callable] = None,
                 store_factory: Optional[Callable] = None,
                 clock_drift: bool = False, journal: bool = True,
                 trace: bool = False, pipeline: bool = False,
                 pipeline_config=None):
        self.random = RandomSource(seed)
        self.queue = PendingQueue(self.random.fork())
        self.network = SimNetwork(self.queue, self.random.fork())
        self.scheduler = SimScheduler(self.queue)
        from accord_tpu.sim.journal import Journal
        self.journal = Journal() if journal else None
        self.token_span = token_span
        self.nodes: Dict[int, Node] = {}
        self.agents: Dict[int, SimAgent] = {}
        rf = rf if rf is not None else n_nodes
        node_ids = list(range(1, n_nodes + 1))
        self.topology = self._make_topology(1, node_ids, n_shards, rf)
        # epoch ledger backing each node's ConfigurationService fetches
        self.topology_ledger: Dict[int, Topology] = {1: self.topology}
        self.config_services: Dict[int, object] = {}
        for nid in node_ids:
            agent = SimAgent(self, nid)
            sink = NodeSink(nid, self.network)
            now_us = (DriftingClock(self.queue.clock, self.random.fork()).now_us
                      if clock_drift
                      else (lambda: self.queue.clock.now_us))
            from accord_tpu.obs import NodeObs
            from accord_tpu.utils.tracing import Trace
            node = Node(
                nid, sink, agent, self.scheduler, ListStore(nid),
                self.random.fork(), num_shards=num_command_stores,
                progress_log_factory=progress_log_factory,
                store_factory=store_factory,
                now_us=now_us,
                trace=Trace(nid, enabled=True,
                            clock=lambda: self.queue.clock.now_us / 1e6)
                if trace else None,
                # span timestamps come from the UNDRIFTED virtual clock:
                # DriftingClock.now_us steps a random walk per call, so
                # clocking obs events through it would perturb the very
                # protocol behavior being observed (and mis-order stitched
                # cross-node traces)
                obs=NodeObs(nid,
                            clock_us=lambda: self.queue.clock.now_us),
            )
            node.journal = self.journal
            self.agents[nid] = agent
            self.nodes[nid] = node
            self.network.register(node)
            # topology flows through the node's ConfigurationService
            # (reference AbstractConfigurationService): the node is a
            # listener, the cluster ledger serves gap fetches
            service = DirectConfigService(nid, self.topology_ledger.get)
            service.attach_node(node)
            self.config_services[nid] = service
            service.report_topology(self.topology)
        # continuous micro-batching ingest (accord_tpu/pipeline/) on every
        # node, deadline-driven by the shared virtual-time scheduler so the
        # deterministic burn can exercise admission batching, MultiPreAccept
        # envelopes and load shedding under the full nemesis stack
        self.pipelines: Dict[int, object] = {}
        if pipeline:
            from accord_tpu.pipeline import Pipeline
            for nid, node in self.nodes.items():
                self.pipelines[nid] = Pipeline(node, self.scheduler,
                                               pipeline_config)

    def pipeline_submit(self, node_id: int, txn):
        """Client entry through the node's ingest pipeline (falls back to
        direct coordination when the pipeline is off)."""
        p = self.pipelines.get(node_id)
        if p is None:
            return self.nodes[node_id].coordinate(txn)
        return p.submit(txn)

    def _make_topology(self, epoch: int, node_ids: List[int], n_shards: int,
                       rf: int) -> Topology:
        width = self.token_span // n_shards
        shards = []
        for i in range(n_shards):
            # rotate replica sets around the ring
            replicas = [node_ids[(i + j) % len(node_ids)] for j in range(rf)]
            shards.append(Shard(Range(i * width, (i + 1) * width), replicas))
        return Topology(epoch, shards)

    def update_topology(self, topology: Topology) -> None:
        self.topology = topology
        self.topology_ledger[topology.epoch] = topology
        for service in self.config_services.values():
            service.report_topology(topology)

    def start_durability_scheduling(self, shard_cycle_s: float = None,
                                    global_cycle_every: int = None) -> None:
        """Run the reference's rotating durability rounds on every node
        (CoordinateDurabilityScheduling.java; burn Cluster.java:333-349)."""
        from accord_tpu.coordinate.durability import \
            CoordinateDurabilityScheduling
        for node in self.nodes.values():
            CoordinateDurabilityScheduling(
                node, shard_cycle_s=shard_cycle_s,
                global_cycle_every=global_cycle_every).start()

    # ----------------------------------------------------------- execution --
    def process_all(self, max_items: int = 1_000_000) -> int:
        return self.queue.drain(max_items=max_items)

    def process_until(self, predicate: Callable[[], bool],
                      max_items: int = 1_000_000) -> bool:
        n = 0
        while n < max_items:
            if predicate():
                return True
            if not self.queue.process_one():
                return predicate()
            n += 1
        return predicate()

    @property
    def now_s(self) -> float:
        return self.queue.clock.now_s()

    def node(self, node_id: int) -> Node:
        return self.nodes[node_id]

    # -------------------------------------------------------- observability --
    def metrics_snapshot(self) -> dict:
        """Cluster-wide obs snapshot: per-node registries merged (counters/
        histograms sum, gauges max) plus the computed summary."""
        from accord_tpu.obs.report import merge_node_snapshots
        return merge_node_snapshots(
            [n.obs.snapshot() for n in self.nodes.values()])

    def stitched_trace(self, trace_id: str):
        """One transaction's span events merged across every replica that
        recorded it: [(at_us, node_id, phase, tags)]."""
        from accord_tpu.obs.spans import stitch
        return stitch([n.obs.spans for n in self.nodes.values()], trace_id)

    def find_trace_ids(self, phase: str = None, **tags):
        from accord_tpu.obs.spans import find_trace_ids
        return find_trace_ids([n.obs.spans for n in self.nodes.values()],
                              phase=phase, **tags)

    def flight_recorders(self):
        return [n.obs.flight for n in self.nodes.values()]

    def stitched_flight(self, trace_ids=None, limit=None):
        """The cross-replica flight timeline (obs/flight.py): every node's
        always-on event ring merged into causal order, optionally filtered
        to a set of trace ids — the failure-forensics view."""
        from accord_tpu.obs.flight import stitch_flight
        return stitch_flight(self.flight_recorders(), trace_ids=trace_ids,
                             limit=limit)
