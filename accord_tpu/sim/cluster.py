"""SimCluster: a whole Accord cluster in one deterministic event loop.

Reference: the burn-test cluster (accord-core test impl/basic/Cluster.java:102,
run loop :277-410): every node's executors, timers and deliveries share one
virtual-time queue; the loop is `while processPending()`.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from accord_tpu.api.spi import Agent, EventsListener
from accord_tpu.impl.config_service import DirectConfigService
from accord_tpu.impl.list_store import ListStore
from accord_tpu.local.node import Node
from accord_tpu.primitives.keys import Range, Ranges
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.network import NodeSink, SimNetwork
from accord_tpu.sim.queue import PendingQueue
from accord_tpu.sim.scheduler import SimScheduler
from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topology import Topology
from accord_tpu.utils.random_source import RandomSource


class SimAgent(Agent):
    def __init__(self, cluster: "SimCluster", node_id: int):
        self.cluster = cluster
        self.node_id = node_id
        self.failures: List[BaseException] = []
        # flipped by SimCluster.kill_node: a ghost's timers (progress-log
        # polls, watchdogs armed pre-crash) keep firing on the discarded
        # object graph — their failures must not abort the simulation
        self.dead = False

    def on_uncaught_exception(self, failure: BaseException) -> None:
        self.failures.append(failure)
        if not self.dead:
            self.cluster.queue.fail(failure)

    def on_handled_exception(self, failure: BaseException) -> None:
        # recorded (so harnesses can assert on incidents like a mid-run
        # device-backend death) but NOT fatal to the simulation
        self.failures.append(failure)

    def pre_accept_timeout(self) -> float:
        return 1.0  # virtual second

    def empty_txn(self, kind: TxnKind, keys_or_ranges) -> Txn:
        return Txn(kind, keys_or_ranges)


class DriftingClock:
    """Per-node wall clock: the shared virtual clock plus a bounded random
    walk (reference BurnTest.java:330-340 — per-node drifting clocks with
    frequent small jumps and occasional large ones, FrequentLargeRange).
    The HLC max-folds regressions away (Node.unique_now), so drift exercises
    timestamp ordering and preaccept-expiry paths without breaking
    monotonicity."""

    def __init__(self, clock, random: RandomSource, small_us: int = 2_000,
                 large_us: int = 10_000, bound_us: int = 50_000):
        self.clock = clock
        self.random = random
        self.small_us = small_us
        self.large_us = large_us
        self.bound_us = bound_us
        self.offset = 0

    def now_us(self) -> int:
        r = self.random
        step = (r.next_int(-self.large_us, self.large_us)
                if r.next_float() < 0.1
                else r.next_int(-self.small_us, self.small_us))
        self.offset = max(-self.bound_us,
                          min(self.bound_us, self.offset + step))
        return max(0, self.clock.now_us + self.offset)


class _DeadSink:
    """Message sink of a killed node's ghost: timers scheduled before the
    kill still fire on the discarded object graph, and whatever they try to
    send must vanish (the process is gone)."""

    def send(self, to, request) -> None:
        pass

    def send_with_callback(self, to, request, callback, executor=None) -> None:
        pass  # no reply ever: the caller's RPC timeout fires

    def reply(self, to, reply_context, reply) -> None:
        pass

    def deliver_reply(self, msg_id, from_id, reply) -> None:
        pass


class SimCluster:
    """N simulated nodes over a token-range topology."""

    def __init__(self, n_nodes: int = 3, seed: int = 0, token_span: int = 1000,
                 n_shards: int = 2, rf: int = None, num_command_stores: int = 1,
                 progress_log_factory: Optional[Callable] = None,
                 store_factory: Optional[Callable] = None,
                 clock_drift: bool = False, journal: bool = True,
                 journal_dir: Optional[str] = None,
                 trace: bool = False, pipeline: bool = False,
                 pipeline_config=None, qos: bool = False, qos_config=None,
                 geo=None, electorate=None):
        self.random = RandomSource(seed)
        self.queue = PendingQueue(self.random.fork())
        self.network = SimNetwork(self.queue, self.random.fork())
        self.scheduler = SimScheduler(self.queue)
        # geo placement (topology/geo.GeoProfile): installs the per-link-
        # class delay matrix into the network and DC/electorate labels
        # into each node's obs; `electorate` (a node-id set) narrows every
        # shard's fast-path electorate to its intersection with the
        # shard's replicas (Shard enforces e >= rf - f).  Neither knob
        # touches the rng fork order, so geo=None stays bit-identical to
        # the pre-geo cluster.
        self.geo = geo
        self._electorate = frozenset(electorate) if electorate else None
        if geo is not None:
            self.network.set_geo(geo)
        # journal_dir turns the in-memory message journal into the REAL
        # write-ahead log (accord_tpu/journal/): per-node on-disk segments
        # in synchronous (deterministic) mode, enabling the crash-restart
        # nemesis — kill_node discards all in-memory state, restart_node
        # rebuilds the replica from its journal directory
        self.journal_dir = journal_dir
        if journal_dir is not None:
            from accord_tpu.journal.wal import DurableJournalSet
            self.journal = DurableJournalSet(journal_dir)
        elif journal:
            from accord_tpu.sim.journal import Journal
            self.journal = Journal()
        else:
            self.journal = None
        self.token_span = token_span
        self.nodes: Dict[int, Node] = {}
        self.agents: Dict[int, SimAgent] = {}
        self.dead: set = set()
        self.restarts = 0
        rf = rf if rf is not None else n_nodes
        node_ids = list(range(1, n_nodes + 1))
        self.topology = self._make_topology(1, node_ids, n_shards, rf)
        # epoch ledger backing each node's ConfigurationService fetches
        self.topology_ledger: Dict[int, Topology] = {1: self.topology}
        self.config_services: Dict[int, object] = {}
        # live replica-state auditors (local/audit.py), one per node once
        # attach_auditors is called; restart_node rebuilds the victim's
        self.auditors: Dict[int, object] = {}
        self._auditor_kw: Optional[dict] = None
        # per-node build args retained so restart_node can rebuild an
        # identically configured replica
        self._num_command_stores = num_command_stores
        self._progress_log_factory = progress_log_factory
        self._store_factory = store_factory
        self._clock_drift = clock_drift
        self._trace_enabled = trace
        # set by start_durability_scheduling; restart_node reuses them
        self._durability_cycle_s = None
        self._durability_global_every = None
        for nid in node_ids:
            self._build_node(nid)
        # continuous micro-batching ingest (accord_tpu/pipeline/) on every
        # node, deadline-driven by the shared virtual-time scheduler so the
        # deterministic burn can exercise admission batching, MultiPreAccept
        # envelopes and load shedding under the full nemesis stack
        self.pipelines: Dict[int, object] = {}
        self._pipeline_enabled = pipeline
        self._pipeline_config = pipeline_config
        # per-tenant QoS admission tiers (accord_tpu/qos/) on every node,
        # clocked by virtual time so the deterministic burn can exercise
        # priority-aware shedding under the full nemesis stack.  Built
        # BEFORE the pipelines: the ingest queue is the tier's last-resort
        # inner ring and tallies its sheds there.
        self.qos_tiers: Dict[int, object] = {}
        self._qos_enabled = qos
        self._qos_config = qos_config
        if qos:
            for nid in self.nodes:
                self._build_qos_tier(nid)
        if pipeline:
            from accord_tpu.pipeline import Pipeline
            for nid, node in self.nodes.items():
                self.pipelines[nid] = Pipeline(node, self.scheduler,
                                               pipeline_config,
                                               qos=self.qos_tiers.get(nid))

    def _build_node(self, nid: int) -> Node:
        """Construct (or reconstruct) one node and wire it to the cluster:
        network registration, config service, journal attachment."""
        agent = SimAgent(self, nid)
        sink = NodeSink(nid, self.network)
        now_us = (DriftingClock(self.queue.clock, self.random.fork()).now_us
                  if self._clock_drift
                  else (lambda: self.queue.clock.now_us))
        from accord_tpu.obs import NodeObs
        from accord_tpu.utils.tracing import Trace
        node = Node(
            nid, sink, agent, self.scheduler, ListStore(nid),
            self.random.fork(), num_shards=self._num_command_stores,
            progress_log_factory=self._progress_log_factory,
            store_factory=self._store_factory,
            now_us=now_us,
            trace=Trace(nid, enabled=True,
                        clock=lambda: self.queue.clock.now_us / 1e6)
            if self._trace_enabled else None,
            # span timestamps come from the UNDRIFTED virtual clock:
            # DriftingClock.now_us steps a random walk per call, so
            # clocking obs events through it would perturb the very
            # protocol behavior being observed (and mis-order stitched
            # cross-node traces)
            obs=NodeObs(nid, clock_us=lambda: self.queue.clock.now_us,
                        dc=self.geo.dc_of(nid) if self.geo else None,
                        elect=("in" if nid in self._electorate else "out")
                        if (self.geo is not None
                            and self._electorate is not None) else None),
        )
        if self.geo is not None:
            # placement is forensics-relevant: a stitched timeline reading
            # a ratio dip needs to know which DC each recorder lived in
            node.obs.flight.record("geo_install", None,
                                   (self.geo.name, node.obs.dc))
        if self.journal_dir is not None:
            self.journal.open_node(nid, registry=node.obs.registry,
                                   flight=node.obs.flight)
        node.journal = self.journal
        self.agents[nid] = agent
        self.nodes[nid] = node
        self.network.register(node)
        # topology flows through the node's ConfigurationService
        # (reference AbstractConfigurationService): the node is a
        # listener, the cluster ledger serves gap fetches
        service = DirectConfigService(nid, self.topology_ledger.get)
        service.attach_node(node)
        self.config_services[nid] = service
        if nid in self.dead:
            # restart: feed the full epoch history (replayed messages gate
            # on their txn's epoch) in DEFER mode — bootstraps are queued,
            # not started, and restart_node's resume_bootstraps() reconciles
            # them against the checkpoint coverage the journal replay
            # restores (re-fetching only what the checkpoints left missing)
            node.defer_bootstrap = True
            for epoch in sorted(self.topology_ledger):
                service.report_topology(self.topology_ledger[epoch])
        else:
            service.report_topology(self.topology)
        return node

    def _build_qos_tier(self, nid: int):
        """Construct (or reconstruct, after restart_node) one node's QoS
        admission tier.  Virtual time has no real loop lag, so the sim's
        deterministic pressure signal is the pipeline ingest depth (looked
        up lazily: the pipelines dict is built after the tiers and
        repopulated on restart)."""
        from accord_tpu.qos import PressureController, QosConfig, QosTier
        node = self.nodes[nid]
        config = self._qos_config if self._qos_config is not None \
            else QosConfig()

        def clock_us() -> int:
            return int(self.queue.clock.now_us)

        def depth_pressure(_nid=nid, _cfg=config) -> float:
            p = self.pipelines.get(_nid)
            return p.ingest.depth / _cfg.depth_target if p is not None \
                else 0.0

        controller = PressureController(config, clock_us,
                                        sources=(depth_pressure,))
        tier = QosTier(config, node.obs.registry, node.obs.flight, clock_us,
                       controller=controller)
        self.qos_tiers[nid] = tier
        return tier

    def pipeline_submit(self, node_id: int, txn, tenant: str = "",
                        priority: str = ""):
        """Client entry through the node's QoS tier (when on) and ingest
        pipeline (falls back to direct coordination when the pipeline is
        off)."""
        tier = self.qos_tiers.get(node_id)
        if tier is not None:
            nack = tier.admit(tenant, priority or "normal")
            if nack is not None:
                from accord_tpu.utils.async_chains import AsyncResult
                result = AsyncResult()
                result.try_failure(nack)
                return result
        p = self.pipelines.get(node_id)
        result = (self.nodes[node_id].coordinate(txn) if p is None
                  else p.submit(txn))
        if tier is not None:
            # admitted op settled (either way): shrink the tier's inflight
            # backlog signal — deterministic, it rides the virtual queue
            result.add_callback(lambda _v, _f: tier.op_done())
        return result

    def _make_topology(self, epoch: int, node_ids: List[int], n_shards: int,
                       rf: int) -> Topology:
        width = self.token_span // n_shards
        shards = []
        for i in range(n_shards):
            # rotate replica sets around the ring
            replicas = [node_ids[(i + j) % len(node_ids)] for j in range(rf)]
            electorate = (frozenset(replicas) & self._electorate
                          if self._electorate else None)
            shards.append(Shard(Range(i * width, (i + 1) * width), replicas,
                                fast_path_electorate=electorate))
        return Topology(epoch, shards)

    def update_topology(self, topology: Topology) -> None:
        self.topology = topology
        self.topology_ledger[topology.epoch] = topology
        for service in self.config_services.values():
            service.report_topology(topology)

    def start_durability_scheduling(self, shard_cycle_s: float = None,
                                    global_cycle_every: int = None) -> None:
        """Run the reference's rotating durability rounds on every node
        (CoordinateDurabilityScheduling.java; burn Cluster.java:333-349)."""
        from accord_tpu.coordinate.durability import \
            CoordinateDurabilityScheduling
        # remembered so a restarted node rejoins the durability rotation
        self._durability_cycle_s = shard_cycle_s
        self._durability_global_every = global_cycle_every
        for node in self.nodes.values():
            CoordinateDurabilityScheduling(
                node, shard_cycle_s=shard_cycle_s,
                global_cycle_every=global_cycle_every).start()

    # ------------------------------------------------------------ auditing --
    def attach_auditors(self, interval_s: float = 0.0,
                        census_interval_s: float = None, **kw) -> None:
        """One replica-state auditor per node (local/audit.py).  With
        interval_s/census_interval_s > 0 the periodic timers arm on the
        shared virtual-time scheduler (the live-audit arm); at 0 the
        auditors are passive and a harness drives audit_once/census_once
        explicitly (the burn's end-of-run checker)."""
        from accord_tpu.local.audit import Auditor
        self._auditor_kw = dict(interval_s=interval_s,
                                census_interval_s=census_interval_s, **kw)
        for nid, node in self.nodes.items():
            if nid in self.dead:
                continue
            a = Auditor(node, **self._auditor_kw)
            a.start()
            self.auditors[nid] = a

    def _attach_auditor(self, nid: int) -> None:
        if self._auditor_kw is None:
            return
        from accord_tpu.local.audit import Auditor
        a = Auditor(self.nodes[nid], **self._auditor_kw)
        a.start()
        self.auditors[nid] = a

    # --------------------------------------------------- crash-restart nemesis --
    def live_node_ids(self) -> List[int]:
        return sorted(set(self.nodes) - self.dead)

    def kill_node(self, node_id: int) -> None:
        """Process-death semantics: every piece of in-memory state —
        command stores, data store, obs rings, pending callbacks — is
        discarded; only the on-disk journal survives.  Requires a durable
        journal (journal_dir), or there would be nothing to restart from.

        The dead Node object is not (cannot be) garbage-collected
        immediately: virtual-time timers scheduled before the kill still
        hold it.  Those ghosts are neutralized, not cancelled — their sink
        drops everything and their agent no longer fails the queue — which
        is exactly a killed process's externally observable behavior."""
        assert self.journal_dir is not None, \
            "kill_node without a durable journal loses acked state"
        assert node_id not in self.dead
        node = self.nodes[node_id]
        self.dead.add(node_id)
        # deliveries to the dead id vanish (SimNetwork checks registration)
        self.network.nodes.pop(node_id, None)
        node.sink = _DeadSink()
        node.journal = None  # a dead process journals nothing
        self.agents[node_id].dead = True
        self.pipelines.pop(node_id, None)
        self.qos_tiers.pop(node_id, None)
        auditor = self.auditors.pop(node_id, None)
        if auditor is not None:
            auditor.stop()
        # close the WAL file handles; un-synced OS buffers survive a
        # process kill, so nothing acked is lost (sync mode anyway)
        self.journal.close_node(node_id)

    def restart_node(self, node_id: int) -> "Node":
        """Bring a killed node back from its journal directory: build a
        fresh replica of the same identity, feed it every ledger epoch
        (start_sync=False — its state comes from the journal, not a peer
        bootstrap), replay the journal through normal message processing,
        and re-register it with the network.  Anything it missed while
        down heals exactly like a partition: later txns' deps name the
        missed ones and the progress log chases them."""
        assert node_id in self.dead, f"node {node_id} is not dead"
        node = self._build_node(node_id)
        self.dead.discard(node_id)
        self.restarts += 1
        wal = self.journal.wals[node_id]
        records = wal.load_records()
        from accord_tpu.journal.replay import replay_node
        replay_node(node, records, registry=node.obs.registry,
                    flight=node.obs.flight)
        # end replay's defer mode: start live bootstraps only for whatever
        # the journaled checkpoints left uncovered
        node.resume_bootstraps()
        if self._durability_cycle_s is not None:
            from accord_tpu.coordinate.durability import \
                CoordinateDurabilityScheduling
            CoordinateDurabilityScheduling(
                node, shard_cycle_s=self._durability_cycle_s,
                global_cycle_every=self._durability_global_every).start()
        if self._qos_enabled:
            self._build_qos_tier(node_id)
        if self._pipeline_enabled:
            from accord_tpu.pipeline import Pipeline
            self.pipelines[node_id] = Pipeline(
                node, self.scheduler, self._pipeline_config,
                qos=self.qos_tiers.get(node_id))
        self._attach_auditor(node_id)
        return node

    # ----------------------------------------------------------- execution --
    def process_all(self, max_items: int = 1_000_000) -> int:
        return self.queue.drain(max_items=max_items)

    def process_until(self, predicate: Callable[[], bool],
                      max_items: int = 1_000_000) -> bool:
        n = 0
        while n < max_items:
            if predicate():
                return True
            if not self.queue.process_one():
                return predicate()
            n += 1
        return predicate()

    @property
    def now_s(self) -> float:
        return self.queue.clock.now_s()

    def node(self, node_id: int) -> Node:
        return self.nodes[node_id]

    # -------------------------------------------------------- observability --
    def metrics_snapshot(self) -> dict:
        """Cluster-wide obs snapshot: per-node registries merged (counters/
        histograms sum, gauges max) plus the computed summary."""
        from accord_tpu.obs.report import merge_node_snapshots
        return merge_node_snapshots(
            [n.obs.snapshot() for n in self.nodes.values()])

    def stitched_trace(self, trace_id: str):
        """One transaction's span events merged across every replica that
        recorded it: [(at_us, node_id, phase, tags)]."""
        from accord_tpu.obs.spans import stitch
        return stitch([n.obs.spans for n in self.nodes.values()], trace_id)

    def find_trace_ids(self, phase: str = None, **tags):
        from accord_tpu.obs.spans import find_trace_ids
        return find_trace_ids([n.obs.spans for n in self.nodes.values()],
                              phase=phase, **tags)

    def flight_recorders(self):
        return [n.obs.flight for n in self.nodes.values()]

    def stitched_flight(self, trace_ids=None, limit=None):
        """The cross-replica flight timeline (obs/flight.py): every node's
        always-on event ring merged into causal order, optionally filtered
        to a set of trace ids — the failure-forensics view."""
        from accord_tpu.obs.flight import stitch_flight
        return stitch_flight(self.flight_recorders(), trace_ids=trace_ids,
                             limit=limit)
