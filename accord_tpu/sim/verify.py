"""Strict-serializability verification for the append-register workload.

Reference: accord-core test verify/StrictSerializabilityVerifier.java:17-58 —
an online happens-before checker over observed per-key append sequences with
real-time bounds and cycle detection.

Model: each committed transaction observed (reads = per-key value tuples,
appends = per-key single values, virtual start/end times). Given the final
per-key histories, strict serializability holds iff:
  1. every read is a prefix of the final per-key order;
  2. every committed append appears exactly once;
  3. a read-modify-write's append lands immediately after its read prefix;
  4. the constraint graph (per-key append order + read-before/after-write
     + real-time precedence) is acyclic.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple


class Observation:
    __slots__ = ("txn_desc", "reads", "appends", "start_us", "end_us")

    def __init__(self, txn_desc, reads: Dict[int, Tuple[int, ...]],
                 appends: Dict[int, int], start_us: int, end_us: int):
        self.txn_desc = txn_desc
        self.reads = dict(reads)      # token -> observed value tuple
        self.appends = dict(appends)  # token -> appended value
        self.start_us = start_us
        self.end_us = end_us

    def __repr__(self):
        return (f"Obs({self.txn_desc}, r={self.reads}, a={self.appends}, "
                f"[{self.start_us},{self.end_us}])")


class Violation(AssertionError):
    pass


class ForensicsMixin:
    """Optional failure-forensics hook shared by the history checkers.

    A harness that owns the cluster's flight recorders (sim/burn.py)
    attaches a callable `forensics(txn_descs) -> str`; every Violation a
    checker raises through `_violation` then carries the stitched
    cross-replica flight timeline for the offending transactions instead
    of (or in addition to) raw state dumps."""

    forensics = None  # Callable[[List[str]], str] | None

    def attach_forensics(self, fn) -> None:
        self.forensics = fn

    def _violation(self, detail: str, txn_descs=(),
                   brief: Optional[str] = None) -> Violation:
        """Build a Violation: `detail` alone without forensics; with a
        forensics hook attached, `brief` (or detail) plus the stitched
        flight timeline.  `brief` lets a checker drop raw state dicts when
        the timeline supersedes them (sim/verify_replay.py)."""
        if self.forensics is not None:
            try:
                extra = self.forensics(list(txn_descs))
            except Exception:  # noqa: BLE001 — forensics must never mask
                extra = ""     # the underlying violation
            if extra:
                return Violation(f"{brief or detail}\n{extra}")
        return Violation(detail)


def real_time_edges(obs: Sequence[Observation], add_edge) -> None:
    """Reduced real-time precedence: a -> every b starting in (end_a, m]
    where m is the minimum end among txns starting after end_a — any
    later-starting txn is reachable transitively through one of those.
    Shared by both checkers (the reduction itself is infrastructure, not
    part of either checking algorithm)."""
    from bisect import bisect_right
    n = len(obs)
    order = sorted(range(n), key=lambda i: obs[i].start_us)
    starts = [obs[i].start_us for i in order]
    suffix_min_end: List[Optional[int]] = [None] * n
    running: Optional[int] = None
    for k in range(n - 1, -1, -1):
        e = obs[order[k]].end_us
        running = e if running is None or e < running else running
        suffix_min_end[k] = running
    for ai in range(n):
        a = order[ai]
        j = bisect_right(starts, obs[a].end_us, lo=ai + 1)
        if j >= n:
            continue
        bound = suffix_min_end[j]
        k = j
        while k < n and starts[k] <= bound:
            add_edge(a, order[k])
            k += 1


class StrictSerializabilityVerifier(ForensicsMixin):
    def __init__(self):
        self.observations: List[Observation] = []

    def observe(self, obs: Observation) -> None:
        self.observations.append(obs)

    def verify(self, final_histories: Dict[int, Sequence[int]]) -> None:
        """Raises Violation with a description on any anomaly."""
        obs = self.observations
        n = len(obs)
        positions: Dict[Tuple[int, int], int] = {}  # (token, value) -> index
        for token, hist in final_histories.items():
            if len(set(hist)) != len(hist):
                raise Violation(f"duplicate value in history of key {token}: {hist}")
            for i, v in enumerate(hist):
                positions[(token, v)] = i

        # 1-3: per-observation checks
        writer_of: Dict[Tuple[int, int], int] = {}  # (token, position) -> obs idx
        for i, o in enumerate(obs):
            for token, value in o.appends.items():
                pos = positions.get((token, value))
                if pos is None:
                    raise self._violation(
                        f"lost append: {o} appended {value} to key {token} "
                        f"but final history is {final_histories.get(token)}",
                        txn_descs=[o.txn_desc])
                dup = writer_of.get((token, pos))
                if dup is not None:
                    raise self._violation(
                        f"two txns own key {token} position {pos}",
                        txn_descs=[obs[dup].txn_desc, o.txn_desc])
                writer_of[(token, pos)] = i
            for token, read in o.reads.items():
                hist = tuple(final_histories.get(token, ()))
                if tuple(read) != hist[:len(read)]:
                    raise self._violation(
                        f"non-prefix read: {o} read {read} of key {token} "
                        f"whose final history is {hist}",
                        txn_descs=[o.txn_desc])
                if token in o.appends:
                    pos = positions[(token, o.appends[token])]
                    if pos != len(read):
                        raise self._violation(
                            f"non-atomic rmw: {o} read prefix of length "
                            f"{len(read)} of key {token} but its append landed "
                            f"at position {pos}", txn_descs=[o.txn_desc])

        # 4: constraint graph acyclicity
        edges: Dict[int, set] = {i: set() for i in range(n)}

        def add_edge(a: int, b: int):
            if a != b:
                edges[a].add(b)

        # per-key append order
        for token, hist in final_histories.items():
            prev: Optional[int] = None
            for pos in range(len(hist)):
                w = writer_of.get((token, pos))
                if w is None:
                    continue  # external/unobserved write
                if prev is not None:
                    add_edge(prev, w)
                prev = w
        # reads: writer(pos < len) -> reader -> writer(pos >= len)
        for i, o in enumerate(obs):
            for token, read in o.reads.items():
                hist = final_histories.get(token, ())
                for pos in range(len(hist)):
                    w = writer_of.get((token, pos))
                    if w is None:
                        continue
                    if pos < len(read):
                        add_edge(w, i)
                    else:
                        add_edge(i, w)
        real_time_edges(obs, add_edge)

        self._check_acyclic(edges)

    def _check_acyclic(self, edges: Dict[int, set]) -> None:
        # Kahn's algorithm; report a cycle member on failure
        indeg = {i: 0 for i in edges}
        for a, outs in edges.items():
            for b in outs:
                indeg[b] += 1
        queue = [i for i, d in indeg.items() if d == 0]
        seen = 0
        while queue:
            a = queue.pop()
            seen += 1
            for b in edges[a]:
                indeg[b] -= 1
                if indeg[b] == 0:
                    queue.append(b)
        if seen != len(edges):
            cyclic = [self.observations[i] for i, d in indeg.items() if d > 0]
            raise self._violation(
                "serialization cycle among "
                f"{[o.txn_desc for o in cyclic[:10]]}"
                f"{'...' if len(cyclic) > 10 else ''}",
                txn_descs=[o.txn_desc for o in cyclic[:10]])
