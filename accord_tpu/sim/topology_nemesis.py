"""TopologyRandomizer: the topology-change nemesis for the burn test.

Reference: accord-core test accord/topology/TopologyRandomizer.java:58,
109-115 — mutates the topology on a virtual-time cadence with UpdateType
{SPLIT, MERGE, MEMBERSHIP, FASTPATH}, exercising epoch sync, bootstrap and
stale-replica handling. Each node learns the new epoch after its own random
delay, so nodes genuinely straddle epochs mid-coordination.
"""

from __future__ import annotations

from typing import Dict, List

from accord_tpu.primitives.keys import Range, Ranges
from accord_tpu.topology.shard import Shard
from accord_tpu.topology.topology import Topology
from accord_tpu.utils.random_source import RandomSource


class TopologyRandomizer:
    def __init__(self, cluster, rng: RandomSource, period_s: float = 2.0,
                 max_changes: int = 1_000_000):
        self.cluster = cluster
        self.rng = rng
        self.period_us = int(period_s * 1e6)
        self.max_changes = max_changes
        self.changes = 0
        self.stopped = False
        # per-node epoch delivery chains (epochs must arrive in order)
        self._pending: Dict[int, List[Topology]] = {
            nid: [] for nid in cluster.nodes}
        self._delivering: Dict[int, bool] = {nid: False for nid in cluster.nodes}

    def start(self) -> None:
        self.cluster.queue.add(self.period_us, self._tick)

    # ------------------------------------------------------------ mutation --
    def stop(self) -> None:
        self.stopped = True

    def _tick(self) -> None:
        if self.stopped or self.changes >= self.max_changes:
            return
        new = self._mutate(self.cluster.topology)
        if new is not None:
            self.changes += 1
            self.cluster.topology = new
            self.cluster.topology_ledger[new.epoch] = new
            for nid in self.cluster.nodes:
                self._enqueue(nid, new)
        self.cluster.queue.add(self.period_us, self._tick)

    def _enqueue(self, nid: int, topology: Topology) -> None:
        self._pending[nid].append(topology)
        if not self._delivering[nid]:
            self._deliver_next(nid)

    def _deliver_next(self, nid: int) -> None:
        if not self._pending[nid]:
            self._delivering[nid] = False
            return
        self._delivering[nid] = True
        topology = self._pending[nid].pop(0)
        delay = 1000 + self.rng.next_int(200_000)  # 1ms..200ms

        def deliver():
            self.cluster.config_services[nid].report_topology(topology)
            self._deliver_next(nid)

        self.cluster.queue.add(delay, deliver)

    def _mutate(self, top: Topology):
        kind = self.rng.pick(["SPLIT", "MERGE", "MEMBERSHIP", "MEMBERSHIP",
                              "FASTPATH"])
        shards = list(top.shards)
        if kind == "SPLIT":
            i = self.rng.next_int(len(shards))
            s = shards[i]
            if s.range.end - s.range.start < 2:
                return None
            mid = s.range.start + 1 + self.rng.next_int(
                s.range.end - s.range.start - 1)
            shards[i:i + 1] = [
                Shard(Range(s.range.start, mid), s.nodes,
                      s.fast_path_electorate, s.joining),
                Shard(Range(mid, s.range.end), s.nodes,
                      s.fast_path_electorate, s.joining),
            ]
        elif kind == "MERGE":
            candidates = [i for i in range(len(shards) - 1)
                          if shards[i].nodes == shards[i + 1].nodes
                          and shards[i].range.end == shards[i + 1].range.start]
            if not candidates:
                return None
            i = self.rng.pick(candidates)
            a, b = shards[i], shards[i + 1]
            shards[i:i + 2] = [Shard(Range(a.range.start, b.range.end),
                                     a.nodes)]
        elif kind == "MEMBERSHIP":
            i = self.rng.next_int(len(shards))
            s = shards[i]
            outsiders = sorted(set(self.cluster.nodes) - set(s.nodes))
            if not outsiders:
                return None
            leave = self.rng.pick(sorted(s.nodes))
            join = self.rng.pick(outsiders)
            nodes = tuple(join if n == leave else n for n in s.nodes)
            shards[i] = Shard(s.range, nodes)
        else:  # FASTPATH
            i = self.rng.next_int(len(shards))
            s = shards[i]
            rf = len(s.nodes)
            f = (rf - 1) // 2
            min_e = rf - f
            size = min_e + self.rng.next_int(rf - min_e + 1)
            electorate = frozenset(self.rng.sample(sorted(s.nodes), size))
            if electorate == s.fast_path_electorate:
                return None
            shards[i] = Shard(s.range, s.nodes, electorate, s.joining)
        return Topology(top.epoch + 1, shards)
