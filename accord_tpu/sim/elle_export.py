"""Export burn/host observations as a jepsen/Elle list-append EDN history.

The reference drives the REAL Elle checker (Clojure) over its histories
(accord-core test verify/ElleVerifier.java:47, deps build.gradle:36-46); our
in-tree port (sim/elle.py) implements the published algorithm but is still
this repo's code.  This exporter closes the oracle-trust gap: it renders the
exact observation stream our checkers consume in the EDN history format the
external Elle tooling (e.g. elle-cli) reads, so a real Elle binary — when one
is available in the environment — can adjudicate the same histories
(tests/test_elle_external.py drives it as a subprocess).

Format (one event map per line, jepsen-style):
    {:index 0, :type :invoke, :process 3, :time 12000, :f :txn,
     :value [[:append 5 1] [:r 5 nil]]}
    {:index 1, :type :ok, ...,  :value [[:append 5 1] [:r 5 [1 2]]]}

Each observation becomes one logical process (clients here are one-shot), so
per-process well-formedness is trivial and Elle's realtime analysis recovers
exactly the completion-before-invocation edges our own checkers use: events
are emitted in virtual-time order with :invoke sorting before :ok at the
same instant.  Same-instant completion/invocation pairs across processes
are therefore treated as CONCURRENT (no realtime edge) — the convention of
sim/verify.real_time_edges, conservative for the checker, and it keeps a
zero-duration op's own :invoke ahead of its :ok (a malformed history
otherwise).
"""

from __future__ import annotations

from typing import List, Sequence


def _micro_ops(obs, invoke: bool) -> str:
    ops: List[str] = []
    for token in sorted(obs.appends):
        ops.append(f"[:append {token} {obs.appends[token]}]")
    for token in sorted(obs.reads):
        if invoke:
            ops.append(f"[:r {token} nil]")
        else:
            vals = " ".join(str(v) for v in obs.reads[token])
            ops.append(f"[:r {token} [{vals}]]")
    return "[" + " ".join(ops) + "]"


def to_edn_history(observations: Sequence) -> str:
    """Render observations (sim/verify.Observation) as an EDN history,
    one event per line, sorted by virtual time."""
    events = []
    for process, obs in enumerate(observations):
        # sort key: (time, phase) with :invoke (0) before :ok (1) at the
        # same instant — same-instant pairs are concurrent (module doc),
        # and a zero-duration op keeps its own invoke→ok order
        events.append((obs.start_us, 0, ":invoke", process,
                       _micro_ops(obs, invoke=True)))
        events.append((obs.end_us, 1, ":ok", process,
                       _micro_ops(obs, invoke=False)))
    events.sort(key=lambda e: (e[0], e[1]))
    lines = []
    for index, (t_us, _phase, etype, process, value) in enumerate(events):
        lines.append(
            "{:index %d, :type %s, :process %d, :time %d, :f :txn, "
            ":value %s}" % (index, etype, process, t_us * 1000, value))
    return "\n".join(lines) + "\n"
