"""Virtual-time pending queue (reference: accord-core test
impl/basic/RandomDelayQueue.java:19, PendingQueue, PropagatingPendingQueue).

A single heap of (virtual_time_us, seq) ordered Pending items; seq breaks ties
deterministically in insertion order. Assertion failures raised inside items
propagate out of the drive loop (PropagatingPendingQueue semantics).
"""

from __future__ import annotations

import heapq
from typing import Callable, List, Optional, Tuple

from accord_tpu.utils.random_source import RandomSource


class Pending:
    __slots__ = ("at_us", "seq", "fn", "cancelled")

    def __init__(self, at_us: int, seq: int, fn: Callable[[], None]):
        self.at_us = at_us
        self.seq = seq
        self.fn = fn
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def __lt__(self, other: "Pending"):
        return (self.at_us, self.seq) < (other.at_us, other.seq)


class RecurringHandle:
    __slots__ = ("cancelled",)

    def __init__(self):
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class SimClock:
    """Virtual microsecond clock owned by the queue."""

    __slots__ = ("now_us",)

    def __init__(self):
        self.now_us = 0

    def now_s(self) -> float:
        return self.now_us / 1e6


class PendingQueue:
    def __init__(self, random: RandomSource = None):
        self.clock = SimClock()
        self._heap: List[Pending] = []
        self._seq = 0
        self._failures: List[BaseException] = []
        self.random = random or RandomSource(0)
        self.processed = 0

    # -- scheduling --
    def add(self, delay_us: int, fn: Callable[[], None]) -> Pending:
        p = Pending(self.clock.now_us + max(0, delay_us), self._seq, fn)
        self._seq += 1
        heapq.heappush(self._heap, p)
        return p

    def add_recurring(self, period_us: int, fn: Callable[[], None]
                      ) -> RecurringHandle:
        handle = RecurringHandle()

        def run():
            if handle.cancelled:
                return
            fn()
            if not handle.cancelled:
                self.add(period_us, run)

        self.add(period_us, run)
        return handle

    def add_random_delay(self, min_us: int, max_us: int,
                         fn: Callable[[], None]) -> Pending:
        delay = min_us if max_us <= min_us else self.random.next_int(min_us, max_us)
        return self.add(delay, fn)

    def fail(self, failure: BaseException) -> None:
        """Record a failure to propagate out of the drive loop."""
        self._failures.append(failure)

    # -- draining --
    @property
    def size(self) -> int:
        return len(self._heap)

    def is_empty(self) -> bool:
        return not self._heap

    def process_one(self) -> bool:
        """Run the next pending item; returns False when drained."""
        while self._heap:
            p = heapq.heappop(self._heap)
            if p.cancelled:
                continue
            self.clock.now_us = max(self.clock.now_us, p.at_us)
            self._run(p)
            self._raise_failures()
            return True
        self._raise_failures()
        return False

    def _run(self, p: Pending) -> None:
        self.processed += 1
        try:
            p.fn()
        except BaseException as e:  # noqa: BLE001 - propagate via drive loop
            self._failures.append(e)

    def _raise_failures(self) -> None:
        if self._failures:
            failure = self._failures[0]
            for extra in self._failures[1:]:
                try:
                    failure.__context__ = extra
                except Exception:
                    pass
            self._failures = []
            raise failure

    def drain(self, until_us: Optional[int] = None, max_items: int = 10_000_000
              ) -> int:
        """Process items until empty / virtual deadline / item budget."""
        n = 0
        while self._heap and n < max_items:
            if until_us is not None and self._heap[0].at_us > until_us:
                break
            if not self.process_one():
                break
            n += 1
        return n
