"""Deterministic discrete-event simulation harness.

Reference: the burn-test cluster (accord-core test impl/basic/Cluster.java:102,
RandomDelayQueue.java:19, NodeSink.java:45, PendingQueue) — SURVEY.md §4a.
Every executor task, timer, and message delivery across a whole simulated
cluster is one Pending item in one seed-deterministic virtual-time queue.
"""

from accord_tpu.sim.queue import Pending, PendingQueue, SimClock
from accord_tpu.sim.scheduler import SimScheduler
