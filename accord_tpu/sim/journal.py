"""Message journal + replay reconstruction: the crash-durability contract.

Reference: accord/local/SerializerSupport.java:60-557 — any Command record is
reconstructible from its SaveStatus plus the node's retained side-effecting
messages — exercised by the burn-test Journal
(accord-core test impl/basic/Journal.java:82-303), which records every
`hasSideEffects` message per node and validates reconstruction round-trips.

Our validator folds each node's journaled messages per txn (order-insensitive:
unions and agreement-checked decided values, which is what makes it robust to
delivery reordering) and asserts that everything the live command state knows
is derivable from the journal:

  * definition     — the journal yields the partial txn's key set
  * executeAt      — every decided-band message agrees on one executeAt,
                     equal to the live command's
  * stable deps    — the live stable deps ids are covered by the journal
                     (live state is a per-store slice of journaled messages)
  * outcome        — PreApplied+ commands have journaled writes covering the
                     live write set
  * invalidation   — INVALIDATED commands have journaled invalidation
                     evidence

A node that could not pass this check could not recover from a crash by
message replay — the durability story the reference's journal certifies.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from accord_tpu.local.status import SaveStatus
from accord_tpu.primitives.timestamp import Timestamp, TxnId


class Journal:
    """Per-node ordered record of side-effecting requests."""

    def __init__(self):
        self.records: Dict[int, List[object]] = {}

    def record(self, node_id: int, request) -> None:
        self.records.setdefault(node_id, []).append(request)

    def for_node(self, node_id: int) -> List[object]:
        return self.records.get(node_id, [])


class Reconstruction:
    """Folded knowledge about one txn from one node's journal."""

    __slots__ = ("txn_id", "witnessed", "definition_keys", "execute_ats",
                 "accept_evidence", "stable_dep_ids", "write_keys",
                 "has_outcome", "invalidated")

    def __init__(self, txn_id: TxnId):
        self.txn_id = txn_id
        self.witnessed = False
        self.definition_keys: Set = set()
        self.execute_ats: Set[Timestamp] = set()   # decided-band only
        self.accept_evidence = False
        self.stable_dep_ids: Set[TxnId] = set()
        self.write_keys: Set = set()
        self.has_outcome = False
        self.invalidated = False


def _keys_of(keys_or_ranges) -> Set:
    try:
        return set(keys_or_ranges)
    except TypeError:
        return set()


def _uncovered(needed: Set, have: Set) -> Set:
    """Elements of `needed` not COVERED by `have`. Exact membership is not
    enough for the range domain: a command's stored body is its message
    body sliced to the store's ranges (e.g. Propagate slices before
    installing), so under topology splits the live body can hold a FRAGMENT
    [0,250) of a journaled definition [0,500) — covered, not missing."""
    from accord_tpu.primitives.keys import Range, Ranges

    missing = needed - have
    if not missing:
        return missing
    have_ranges = Ranges([h for h in have if isinstance(h, Range)])
    if have_ranges.is_empty:
        return missing
    out = set()
    for n in missing:
        if isinstance(n, Range):
            if not Ranges([n]).subtract(have_ranges).is_empty:
                out.add(n)
        elif not have_ranges.contains(n):
            out.add(n)
    return out


def reconstruct(records: List[object]) -> Dict[TxnId, Reconstruction]:
    """Fold a node's journal into per-txn reconstructed knowledge
    (SerializerSupport.reconstruct's message-picking, as one pass)."""
    from accord_tpu.messages.accept import Accept, AcceptInvalidate
    from accord_tpu.messages.apply_msg import Apply
    from accord_tpu.messages.commit import Commit, CommitInvalidate
    from accord_tpu.messages.invalidate_msg import BeginInvalidation
    from accord_tpu.messages.preaccept import PreAccept
    from accord_tpu.messages.propagate import Propagate
    from accord_tpu.messages.recover import BeginRecovery

    out: Dict[TxnId, Reconstruction] = {}

    def rec(txn_id: TxnId) -> Reconstruction:
        r = out.get(txn_id)
        if r is None:
            r = out[txn_id] = Reconstruction(txn_id)
        return r

    for msg in records:
        txn_id = getattr(msg, "txn_id", None)
        if txn_id is None:
            continue
        r = rec(txn_id)
        r.witnessed = True
        if isinstance(msg, PreAccept):
            if msg.partial_txn is not None:
                r.definition_keys |= _keys_of(msg.partial_txn.keys)
        elif isinstance(msg, Accept):
            r.accept_evidence = True
        elif isinstance(msg, (AcceptInvalidate, BeginInvalidation)):
            r.accept_evidence = True
        elif isinstance(msg, Commit):
            r.execute_ats.add(msg.execute_at)
            if msg.partial_txn is not None:
                r.definition_keys |= _keys_of(msg.partial_txn.keys)
            if msg.kind.is_stable:
                r.stable_dep_ids |= msg.deps.txn_id_set()
        elif isinstance(msg, CommitInvalidate):
            r.invalidated = True
        elif isinstance(msg, Apply):
            r.execute_ats.add(msg.execute_at)
            if msg.partial_txn is not None:
                r.definition_keys |= _keys_of(msg.partial_txn.keys)
            if msg.deps is not None:
                r.stable_dep_ids |= msg.deps.txn_id_set()
            if msg.writes is not None:
                r.has_outcome = True
                r.write_keys |= _keys_of(msg.writes.keys)
        elif isinstance(msg, BeginRecovery):
            r.accept_evidence = True
            if msg.partial_txn is not None:
                r.definition_keys |= _keys_of(msg.partial_txn.keys)
        elif isinstance(msg, Propagate):
            k = msg.known
            if k.save_status == SaveStatus.INVALIDATED:
                r.invalidated = True
                continue
            if k.partial_txn is not None:
                r.definition_keys |= _keys_of(k.partial_txn.keys)
            if k.execute_at is not None \
                    and k.save_status >= SaveStatus.PRE_COMMITTED:
                r.execute_ats.add(k.execute_at)
            if k.stable_deps is not None:
                r.stable_dep_ids |= k.stable_deps.txn_id_set()
            if k.writes is not None:
                r.has_outcome = True
                r.write_keys |= _keys_of(k.writes.keys)
    return out


def reconstruct_durable_bounds(records: List[object]):
    """Fold the journaled durability-watermark messages into a DurableBefore
    — the knowledge a crash-replay re-derives the safe-to-clean inference
    from (local/cleanup.py INVALIDATE_THEN_ERASE: an undecided straggler
    below the replayed universal bound is re-inferred invalid by the sweep,
    with no per-txn invalidation record ever journaled)."""
    from accord_tpu.local.watermarks import DurableBefore
    from accord_tpu.messages.durability import (SetGloballyDurable,
                                                SetShardDurable)
    from accord_tpu.primitives.timestamp import TXNID_NONE

    db = DurableBefore()
    for msg in records:
        if isinstance(msg, SetShardDurable):
            db.update(msg.ranges, msg.txn_id,
                      msg.txn_id if msg.universal else TXNID_NONE)
        elif isinstance(msg, SetGloballyDurable):
            db.update(msg.ranges, msg.majority, msg.universal)
    return db


def _universal_bound_covers(db, store, cmd) -> bool:
    """Would the replayed universal bound re-infer this command invalid?
    Mirrors cleanup.should_cleanup's INVALIDATE_THEN_ERASE predicate
    against the journal-reconstructed DurableBefore."""
    from accord_tpu.local import cleanup
    participants = cleanup._participants(store, cmd)
    if participants is None:
        return False
    from accord_tpu.primitives.keys import Ranges
    if isinstance(participants, Ranges):
        _maj, uni = db.min_bounds(participants)
        return cmd.txn_id < uni
    return len(participants) > 0 and all(
        db.is_universally_durable(cmd.txn_id, k) for k in participants)


def validate_node(node) -> Tuple[int, int]:
    """Assert every live command on `node` is reconstructible from its
    journal. Returns (commands_checked, commands_skipped)."""
    records = node.journal.for_node(node.id)
    recons = reconstruct(records)
    durable_bounds = None  # folded lazily: most runs never need it
    checked = skipped = 0
    for store in node.command_stores.all():
        for txn_id, cmd in store.commands.items():
            st = cmd.save_status
            if st == SaveStatus.NOT_DEFINED or st.is_truncated \
                    or txn_id.kind.name == "LOCAL_ONLY":
                skipped += 1  # nothing durable to reconstruct / local marker
                continue
            r = recons.get(txn_id)
            ctx = f"node {node.id} store {store.id} {txn_id!r} {st.name}"
            if st == SaveStatus.INVALIDATED:
                ok = r is not None and (r.invalidated or r.accept_evidence)
                if not ok:
                    # safe-to-clean inference (coordinate/infer.py): no
                    # per-txn record exists, but replaying the journaled
                    # SetShardDurable/SetGloballyDurable bounds re-infers
                    # the invalidation deterministically
                    if durable_bounds is None:
                        durable_bounds = reconstruct_durable_bounds(records)
                    ok = _universal_bound_covers(durable_bounds, store, cmd)
                assert ok, f"{ctx}: invalidation not journaled"
                checked += 1
                continue
            assert r is not None and r.witnessed, f"{ctx}: never journaled"
            if cmd.partial_txn is not None:
                missing = _uncovered(_keys_of(cmd.partial_txn.keys),
                                     r.definition_keys)
                assert not missing, \
                    f"{ctx}: definition keys {missing} not journaled"
            if st >= SaveStatus.PRE_COMMITTED and cmd.execute_at is not None:
                assert len(r.execute_ats) <= 1, \
                    f"{ctx}: divergent journaled executeAts {r.execute_ats}"
                assert r.execute_ats == {cmd.execute_at}, \
                    (f"{ctx}: live executeAt {cmd.execute_at!r} vs journal "
                     f"{r.execute_ats}")
            elif st in (SaveStatus.ACCEPTED, SaveStatus.ACCEPTED_INVALIDATE):
                assert r.accept_evidence, f"{ctx}: accept not journaled"
            if st >= SaveStatus.STABLE and cmd.stable_deps is not None:
                live_ids = cmd.stable_deps.txn_id_set()
                missing = live_ids - r.stable_dep_ids
                assert not missing, \
                    f"{ctx}: stable dep ids {missing} not journaled"
            if st >= SaveStatus.PRE_APPLIED and cmd.writes is not None:
                assert r.has_outcome, f"{ctx}: outcome not journaled"
                missing = _keys_of(cmd.writes.keys) - r.write_keys
                assert not missing, \
                    f"{ctx}: write keys {missing} not journaled"
            checked += 1
    return checked, skipped


def validate_cluster(cluster) -> Tuple[int, int]:
    checked = skipped = 0
    for node in cluster.nodes.values():
        c, s = validate_node(node)
        checked += c
        skipped += s
    return checked, skipped
