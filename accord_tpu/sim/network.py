"""Simulated network: per-link nemesis actions and randomized delays.

Reference: accord-core test impl/basic/NodeSink.java:45 (Action {DELIVER,
DROP, DELIVER_WITH_FAILURE, FAILURE}), Cluster.java:518+ (partition
generator / LinkConfig). All deliveries are Pending items in the shared
virtual-time queue, so message interleavings derive entirely from the seed.
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, Optional, Tuple

from accord_tpu.api.spi import CallbackSink, MessageSink
from accord_tpu.messages.base import FailureReply, Reply, Request
from accord_tpu.sim.queue import PendingQueue
from accord_tpu.utils.random_source import RandomSource


class Action(enum.Enum):
    DELIVER = "DELIVER"
    DROP = "DROP"
    DELIVER_WITH_FAILURE = "DELIVER_WITH_FAILURE"  # deliver, but fail the response path
    FAILURE = "FAILURE"                            # fail without delivering


class LinkConfig:
    """Per-ordered-pair link behavior."""

    def __init__(self, deliver_prob: float = 1.0, min_delay_us: int = 500,
                 max_delay_us: int = 20_000, down: bool = False):
        self.deliver_prob = deliver_prob
        self.min_delay_us = min_delay_us
        self.max_delay_us = max_delay_us
        self.down = down

    def action(self, random: RandomSource) -> Action:
        if self.down:
            return Action.DROP
        if random.next_float() < self.deliver_prob:
            return Action.DELIVER
        return Action.DROP


class SimNetwork:
    def __init__(self, queue: PendingQueue, random: RandomSource):
        self.queue = queue
        self.random = random
        self.nodes: Dict[int, object] = {}          # node_id -> Node
        self.links: Dict[Tuple[int, int], LinkConfig] = {}
        self.default_link = LinkConfig()
        self.stats: Dict[str, int] = {}
        self.on_deliver: Optional[Callable] = None  # tracing hook
        # drop filters: fn(from_id, to_id, message) -> True to drop
        # (reference test/accord/NetworkFilter)
        self.filters: list = []
        # geo placement (topology/geo.GeoProfile): when installed, the
        # per-(src,dst) link-class bounds replace the flat default-link
        # delay draw — still exactly one bounded next_int per delivery, so
        # the run stays deterministic per seed; explicit set_link overrides
        # (nemesis partitions, bespoke test links) still win
        self.geo = None

    def set_geo(self, profile) -> None:
        self.geo = profile

    def add_filter(self, fn: Callable) -> Callable:
        self.filters.append(fn)
        return fn

    def remove_filter(self, fn: Callable) -> None:
        if fn in self.filters:
            self.filters.remove(fn)

    def _filtered(self, from_id: int, to_id: int, message) -> bool:
        return any(f(from_id, to_id, message) for f in self.filters)

    def register(self, node) -> None:
        self.nodes[node.id] = node

    def link(self, from_id: int, to_id: int) -> LinkConfig:
        return self.links.get((from_id, to_id), self.default_link)

    def set_link(self, from_id: int, to_id: int, config: LinkConfig) -> None:
        self.links[(from_id, to_id)] = config

    def partition(self, group_a, group_b) -> None:
        """Sever links between two node groups (both directions)."""
        for a in group_a:
            for b in group_b:
                self.set_link(a, b, LinkConfig(down=True))
                self.set_link(b, a, LinkConfig(down=True))

    def heal(self) -> None:
        self.links.clear()

    def _count(self, what: str) -> None:
        self.stats[what] = self.stats.get(what, 0) + 1

    def _record_drop(self, from_id: int, to_id: int, message,
                     msg_name: str) -> None:
        """Drops are recorded on the SENDER's flight ring (the receiver
        never saw the message; the sender's timeline is where the gap shows
        up next to its tx event)."""
        node = self.nodes.get(from_id)
        obs = getattr(node, "obs", None)
        if obs is not None:
            obs.flight.record("drop", getattr(message, "trace_id", None),
                              (from_id, to_id, msg_name))

    def _delay_us(self, from_id: int, to_id: int, link: LinkConfig) -> int:
        """Per-delivery delay draw.  With a geo profile installed and no
        explicit link override for this pair, the (src,dst) link-class
        bounds govern; otherwise the link's own bounds (the pre-geo flat
        path, bit-identical in rng consumption)."""
        if self.geo is not None and (from_id, to_id) not in self.links:
            bounds = self.geo.delay_bounds_us(from_id, to_id)
            if bounds is not None:
                lo, hi = bounds
                return lo if hi <= lo else self.random.next_int(lo, hi)
        return (link.min_delay_us
                if link.max_delay_us <= link.min_delay_us
                else self.random.next_int(link.min_delay_us, link.max_delay_us))

    def _count_link_class(self, from_id: int, to_id: int) -> None:
        """Per-link-class message census on the SENDER's registry — the
        messages/txn x link-class yardstick (WAN crossings/txn) the wan
        report section folds.  Only active under a geo profile."""
        cls = self.geo.link_class(from_id, to_id)
        if cls is None:
            return
        node = self.nodes.get(from_id)
        obs = getattr(node, "obs", None)
        if obs is not None:
            obs.registry.counter("accord_link_msgs_total", cls=cls).inc()

    def deliver_request(self, from_id: int, to_id: int, request: Request,
                        reply_context) -> None:
        link = self.link(from_id, to_id)
        action = link.action(self.random)
        msg_name = type(request).__name__
        if action == Action.DROP or self._filtered(from_id, to_id, request):
            self._count(f"drop.{msg_name}")
            self._record_drop(from_id, to_id, request, msg_name)
            return
        self._count(f"deliver.{msg_name}")
        if self.geo is not None:
            self._count_link_class(from_id, to_id)
        delay = self._delay_us(from_id, to_id, link)

        def run():
            node = self.nodes.get(to_id)
            if node is None:
                return
            if self.on_deliver is not None:
                self.on_deliver(from_id, to_id, request)
            node.receive(request, from_id, reply_context)

        self.queue.add(delay, run)

    def deliver_reply(self, from_id: int, to_id: int, msg_id: int,
                      reply: Reply) -> None:
        link = self.link(from_id, to_id)
        if link.action(self.random) == Action.DROP \
                or self._filtered(from_id, to_id, reply):
            self._count(f"drop.{type(reply).__name__}")
            self._record_drop(from_id, to_id, reply, type(reply).__name__)
            return
        self._count(f"deliver.{type(reply).__name__}")
        if self.geo is not None:
            self._count_link_class(from_id, to_id)
        delay = self._delay_us(from_id, to_id, link)

        def run():
            node = self.nodes.get(to_id)
            if node is None:
                return
            sink: NodeSink = node.sink
            sink.deliver_reply(msg_id, from_id, reply)

        self.queue.add(delay, run)


class PartitionNemesis:
    """Periodically severs the cluster into two groups and heals after a
    random interval (reference Cluster.java:518+ schedules re-partitioning
    every 5s virtual). Alternates partition/heal ticks; `stop()` heals and
    cancels, letting the burn quiesce."""

    def __init__(self, network: SimNetwork, queue: PendingQueue,
                 random: RandomSource, node_ids,
                 period_s: float = 5.0, max_partition_s: float = 4.0):
        self.network = network
        self.queue = queue
        self.random = random
        self.node_ids = sorted(node_ids)
        self.period_us = int(period_s * 1e6)
        self.max_partition_us = int(max_partition_s * 1e6)
        self.partitioned = False
        self.partitions_applied = 0
        self._stopped = False

    def start(self) -> None:
        self.queue.add(self.random.next_int(0, self.period_us), self._tick)

    def stop(self) -> None:
        self._stopped = True
        if self.partitioned:
            self._heal()

    def _heal(self) -> None:
        self.network.heal()
        self.partitioned = False

    def _tick(self) -> None:
        if self._stopped:
            return
        if self.partitioned:
            self._heal()
            self.queue.add(self.random.next_int(1, self.period_us), self._tick)
            return
        ids = list(self.node_ids)
        if len(ids) >= 2:
            self.random.shuffle(ids)
            cut = 1 + self.random.next_int(len(ids) - 1)
            self.network.partition(ids[:cut], ids[cut:])
            self.partitioned = True
            self.partitions_applied += 1
        self.queue.add(self.random.next_int(1, self.max_partition_us),
                       self._tick)


class DcPartitionNemesis:
    """Periodically severs ONE whole datacenter from the rest of the
    cluster and heals it (virtual-time ticks like PartitionNemesis, which
    cuts random groups).  Every begin/heal is recorded on each live node's
    flight ring (`dc_partition_begin` / `dc_partition_heal`) so a stitched
    timeline explains exactly when and why the fast-path ratio dipped: a
    partitioned electorate member makes the fast quorum unreachable while
    a hub-local slow quorum keeps committing on the slow path.

    `partition_now(dc)` / `heal_now()` are public so a bench lane can
    drive deterministic degrade/heal windows without the random ticker."""

    def __init__(self, network: SimNetwork, queue: PendingQueue,
                 random: RandomSource, geo, dcs=None,
                 period_s: float = 5.0, max_partition_s: float = 4.0):
        self.network = network
        self.queue = queue
        self.random = random
        self.geo = geo
        # DCs eligible for partitioning (default: every named DC)
        self.dcs = sorted(dcs) if dcs else sorted(geo.dcs)
        self.period_us = int(period_s * 1e6)
        self.max_partition_us = int(max_partition_s * 1e6)
        self.partitioned_dc: str = ""
        self.partitions_applied = 0
        self._stopped = False

    def start(self) -> None:
        self.queue.add(self.random.next_int(0, self.period_us), self._tick)

    def stop(self) -> None:
        self._stopped = True
        if self.partitioned_dc:
            self.heal_now()

    def partition_now(self, dc: str) -> None:
        inside = self.geo.nodes_in(dc)
        outside = [n for n in self.network.nodes if n not in inside]
        self.network.partition(inside, outside)
        self.partitioned_dc = dc
        self.partitions_applied += 1
        data = (dc, tuple(inside))
        for obs in self._all_obs():
            obs.flight.record("dc_partition_begin", None, data)

    def heal_now(self) -> None:
        dc, self.partitioned_dc = self.partitioned_dc, ""
        self.network.heal()
        data = (dc, tuple(self.geo.nodes_in(dc)))
        for obs in self._all_obs():
            obs.flight.record("dc_partition_heal", None, data)

    def _all_obs(self):
        return [obs for obs in
                (getattr(node, "obs", None)
                 for node in self.network.nodes.values())
                if obs is not None]

    def _tick(self) -> None:
        if self._stopped:
            return
        if self.partitioned_dc:
            self.heal_now()
            self.queue.add(self.random.next_int(1, self.period_us),
                           self._tick)
            return
        self.partition_now(self.dcs[self.random.next_int(len(self.dcs))])
        self.queue.add(self.random.next_int(1, self.max_partition_us),
                       self._tick)


class NodeSink(CallbackSink):
    """MessageSink bound to one simulated node."""

    def __init__(self, node_id: int, network: SimNetwork):
        super().__init__()
        self.node_id = node_id
        self.network = network

    def send(self, to: int, request: Request) -> None:
        if self._capture(to, None, request):
            return
        self.network.deliver_request(self.node_id, to, request, None)

    def send_with_callback(self, to: int, request: Request, callback,
                           executor=None) -> None:
        msg_id = self._register(callback)
        ctx = (self.node_id, msg_id)
        if self._capture(to, ctx, request):
            return
        self.network.deliver_request(self.node_id, to, request, ctx)

    def _send_prepared(self, to: int, reply_context, request) -> None:
        self.network.deliver_request(self.node_id, to, request,
                                     reply_context)

    def reply(self, to: int, reply_context, reply: Reply) -> None:
        if reply_context is None:
            return
        origin, msg_id = reply_context
        self.network.deliver_reply(self.node_id, origin, msg_id, reply)
