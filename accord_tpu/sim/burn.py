"""The burn test: randomized full-cluster simulation with verification.

Reference: accord-core test burn/BurnTest.java:316-553 + impl/basic/Cluster
(SURVEY.md §4a): a seeded workload of multi-key reads/writes/RMWs driven
through a simulated cluster; every response feeds the strict-serializability
verifier; acks/nacks/timeouts are tallied and asserted non-pathological;
everything derives from one seed (`--loop-seed` reproduction).

Usage:  python -m accord_tpu.sim.burn -s SEED -o OPS [--nodes N] [--drop P]
"""

from __future__ import annotations

import argparse
import math
import sys
from typing import Dict, List, Optional, Tuple

from accord_tpu.impl.list_store import (ListQuery, ListRangeRead, ListRead,
                                        ListResult, ListUpdate)
from accord_tpu.primitives.keys import Key, Keys, Ranges
from accord_tpu.primitives.timestamp import TxnKind
from accord_tpu.primitives.txn import Txn
from accord_tpu.sim.cluster import SimCluster
from accord_tpu.sim.network import LinkConfig
from accord_tpu.sim.verify import Observation, StrictSerializabilityVerifier
from accord_tpu.utils.random_source import RandomSource


class BurnStats:
    def __init__(self):
        self.acks = 0
        self.nacks = 0
        # pipeline admission sheds (typed Rejected: never coordinated, safe
        # to retry) — surfaced in the summary as their own tally instead of
        # being folded into nacks, so a shedding run is distinguishable
        # from a failing one
        self.shed = 0
        self.lost = 0
        self.pending = 0
        # crash-restart nemesis: nodes killed (process death) and brought
        # back from their on-disk journal mid-run
        self.restarts = 0
        # submit->ack VIRTUAL latency per acked op (us): the measurement for
        # SURVEY §7's flush-window-latency hard part — the batched device
        # store must not inflate the fast path's single-round-trip advantage
        self.ack_latencies_us: list = []

    def latency_us(self, pct: float) -> int:
        """Nearest-rank percentile (0..100] of acked-op latency; -1 with no
        acks."""
        if not self.ack_latencies_us:
            return -1
        s = sorted(self.ack_latencies_us)
        rank = math.ceil(len(s) * pct / 100.0)
        return s[min(len(s) - 1, max(0, rank - 1))]

    def __repr__(self):
        return (f"acks={self.acks} nacks={self.nacks} shed={self.shed} "
                f"lost={self.lost} pending={self.pending}"
                + (f" restarts={self.restarts}" if self.restarts else ""))


class BurnRun:
    def __init__(self, seed: int, ops: int, nodes: int = 3, keys: int = 20,
                 drop_prob: float = 0.0, rf: int = None, n_shards: int = 4,
                 concurrency: int = 8,
                 progress_log_factory="default", num_command_stores: int = 1,
                 range_reads: bool = True, range_every: int = 8,
                 durability: bool = True,
                 durability_cycle_s: float = None,
                 topology_changes: bool = True,
                 topology_period_s: float = 3.0,
                 store_factory=None,
                 partitions: bool = False,
                 partition_period_s: float = 8.0,
                 clock_drift: bool = False,
                 trace: bool = False,
                 pipeline: bool = False,
                 pipeline_config=None,
                 qos: bool = False,
                 qos_config=None,
                 restarts: int = 0,
                 journal_dir: Optional[str] = None,
                 restart_down_s: float = 2.0,
                 eph_ratio: float = 0.0,
                 audit: bool = True,
                 audit_live_s: float = 0.0,
                 census_live_s: float = 0.0,
                 audit_kw: Optional[dict] = None,
                 corrupt_at: Optional[int] = None,
                 corrupt_invalidated: bool = False,
                 geo=None,
                 electorate=None,
                 dc_partitions: bool = False,
                 dc_partition_period_s: float = 2.0):
        if progress_log_factory == "default":
            # the progress log is a required component under message loss: an
            # acked txn whose Apply messages are all dropped is only repaired
            # by recovery (the reference burn always runs SimpleProgressLog)
            from accord_tpu.impl.progress_log import SimpleProgressLog
            progress_log_factory = SimpleProgressLog
        self.seed = seed
        self.ops = ops
        self.rng = RandomSource(seed)
        # crash-restart nemesis needs a REAL journal to restart from: a
        # killed node's in-memory state is discarded wholesale (process
        # death), so the cluster journal becomes per-node on-disk WALs
        # (accord_tpu/journal/) instead of the in-memory message list
        self.restarts = restarts
        self.restart_down_s = restart_down_s
        if restarts > 0 and journal_dir is None:
            import tempfile
            journal_dir = tempfile.mkdtemp(prefix="accord-burn-wal-")
        self.journal_dir = journal_dir
        self.cluster = SimCluster(
            n_nodes=nodes, seed=self.rng.next_long(), n_shards=n_shards,
            rf=rf, progress_log_factory=progress_log_factory,
            num_command_stores=num_command_stores,
            store_factory=store_factory, clock_drift=clock_drift,
            journal_dir=journal_dir,
            trace=trace, pipeline=pipeline,
            pipeline_config=pipeline_config,
            qos=qos, qos_config=qos_config,
            geo=geo, electorate=electorate)
        # QoS arm: ops carry a randomized tenant (t0..t2) and priority
        # class; per-class outcomes are tallied CLIENT-side (exact across
        # crash-restarts, which reset a node's registry counters) so the
        # fairness invariant — high is never QoS-shed while best_effort is
        # being admitted — is assertable from the run alone
        self.qos = qos
        self.qos_class_stats: Dict[str, Dict[str, int]] = {}
        if drop_prob > 0:
            self.cluster.network.default_link = LinkConfig(
                deliver_prob=1.0 - drop_prob)
        self.partition_nemesis = None
        if partitions:
            from accord_tpu.sim.network import PartitionNemesis
            self.partition_nemesis = PartitionNemesis(
                self.cluster.network, self.cluster.queue, self.rng.fork(),
                list(self.cluster.nodes), period_s=partition_period_s)
            self.partition_nemesis.start()
        # DC-partition nemesis (geo arm): periodically sever one whole
        # datacenter and heal it — the fast-path ratio degrades while an
        # electorate DC is dark and recovers after heal; every begin/heal
        # lands on the flight rings (dc_partition_begin/heal)
        self.dc_partition_nemesis = None
        if dc_partitions:
            assert geo is not None, "dc_partitions needs a geo profile"
            from accord_tpu.sim.network import DcPartitionNemesis
            self.dc_partition_nemesis = DcPartitionNemesis(
                self.cluster.network, self.cluster.queue, self.rng.fork(),
                geo, period_s=dc_partition_period_s)
            self.dc_partition_nemesis.start()
        self.keys = keys
        self.concurrency = concurrency
        self.range_reads = range_reads
        self.range_every = range_every
        # read-heavy ephemeral lane (ISSUE 6): this fraction of ops become
        # single-key Zipf reads on the EPHEMERAL_READ path, putting the
        # never-witnessed single-round read under the full nemesis stack
        # (the default mix only reaches it via occasional 1-key pure reads)
        self.eph_ratio = eph_ratio
        if durability:
            # randomized cadence like the reference burn (Cluster.java:333)
            cycle = (durability_cycle_s if durability_cycle_s is not None
                     else 5.0 + self.rng.next_float() * 25.0)
            self.cluster.start_durability_scheduling(shard_cycle_s=cycle)
        self.nemesis = None
        if topology_changes:
            from accord_tpu.sim.topology_nemesis import TopologyRandomizer
            self.nemesis = TopologyRandomizer(self.cluster, self.rng.fork(),
                                              period_s=topology_period_s)
            self.nemesis.start()
        # three unrelated checking algorithms must all pass, like the
        # reference's own verifier composed with Elle (CompositeVerifier +
        # ElleVerifier.java:47): cycle detection on the constraint graph,
        # explicit witness construction + model replay, and the ported
        # Elle list-append analysis (sim/elle.py — version orders inferred
        # from reads, SCC cycle search, anomaly classification)
        from accord_tpu.sim.verify_replay import full_verifier
        self.verifier = full_verifier()
        # failure forensics (obs/flight.py): acked results map their client
        # txn_desc to the protocol trace id (ListResult carries the TxnId),
        # so a checker Violation naming an observation stitches that txn's
        # cross-replica flight timeline into the failure artifact
        self.verifier.attach_forensics(self._forensics)
        self._trace_of_desc: Dict[str, str] = {}
        self.flight_artifact: Optional[str] = None
        self._last_forensics_events = None
        # test hook: mutate the observation list before verification (an
        # injected invariant violation exercising the forensics path —
        # tests/test_flight.py)
        self.fault_injector = None
        # replica-state auditor (local/audit.py): passive auditors on every
        # node for the ALWAYS-ON end-of-run digest+census checker; live
        # periodic auditing (the production cadence) via audit_live_s /
        # census_live_s.  The corruption nemesis (sim/corruption.py)
        # silently mutates one replica's decided state mid-run — the
        # divergence the end-of-run checker must then report.
        self.audit = audit
        if audit:
            self.cluster.attach_auditors(interval_s=audit_live_s,
                                         census_interval_s=census_live_s,
                                         **(audit_kw or {}))
        self._corrupt_at = corrupt_at
        self._corrupt_invalidated = corrupt_invalidated
        self.corrupted_txn = None
        self.corrupted_node: Optional[int] = None
        self.audit_rounds: list = []
        self.stats = BurnStats()
        self.next_value = 0
        self._value_owner: Dict[int, dict] = {}
        # crash-restart nemesis schedule: kill #i fires once the completed-
        # op count crosses its threshold (mid-run by construction), restart
        # follows restart_down_s of virtual downtime later
        self._kill_at = [self.ops * (i + 1) // (restarts + 1)
                         for i in range(restarts)]
        self.restarted_nodes: List[int] = []

    # ---------------------------------------------------------- workload --
    def _gen_txn(self) -> Txn:
        rng = self.rng
        if self.eph_ratio and rng.next_float() < self.eph_ratio:
            token = rng.next_zipf(self.keys)
            return Txn(TxnKind.EPHEMERAL_READ, Keys.of(token),
                       read=ListRead(Keys.of(token)), query=ListQuery())
        # ~1 in range_every ops: a range read over a token window (the
        # reference burn mixes range queries in, BurnTest.java:124-210)
        if self.range_reads and rng.next_int(0, self.range_every) == 0:
            lo = rng.next_int(0, self.keys - 1)
            hi = min(self.keys, lo + 1 + rng.next_int(1, max(2, self.keys // 4)))
            ranges = Ranges.of((lo, hi))
            return Txn(TxnKind.READ, ranges, read=ListRangeRead(ranges),
                       query=ListQuery())
        n_read = rng.next_int(0, 3)
        n_write = rng.next_int(0, 3) if n_read else rng.next_int(1, 3)
        read_tokens = {rng.next_zipf(self.keys) for _ in range(n_read)}
        write_tokens = {rng.next_zipf(self.keys) for _ in range(n_write)}
        appends = {}
        for t in write_tokens:
            appends[t] = self.next_value
            self.next_value += 1
        all_tokens = read_tokens | write_tokens
        # RMWs read what they write (the strongest check)
        read_set = read_tokens | (write_tokens if rng.next_bool() else set())
        if not appends and len(read_set) == 1:
            # single-key pure reads go the ephemeral (single-round, invisible)
            # path, as the reference burn does (BurnTest.java:124-210)
            return Txn(TxnKind.EPHEMERAL_READ, Keys.of(*read_set),
                       read=ListRead(Keys.of(*read_set)), query=ListQuery())
        return Txn(
            TxnKind.WRITE if appends else TxnKind.READ,
            Keys.of(*all_tokens),
            read=ListRead(Keys.of(*read_set)) if read_set else None,
            query=ListQuery(),
            update=ListUpdate({Key(t): v for t, v in appends.items()})
            if appends else None)

    # -------------------------------------------------- crash-restart -----
    def _maybe_kill(self) -> None:
        """Fire the next scheduled kill once enough ops completed.  The
        kill itself runs as its own queue event (not inside a client
        callback's stack), the restart after `restart_down_s` of virtual
        downtime.  Kills never overlap: a due threshold waits while a
        previous victim is still down."""
        if not self._kill_at or self.cluster.dead:
            return
        done_ops = (self.stats.acks + self.stats.nacks + self.stats.shed
                    + self.stats.lost)
        if done_ops < self._kill_at[0]:
            return
        self._kill_at.pop(0)
        victim = self.rng.pick(self.cluster.live_node_ids())
        down_us = int(self.restart_down_s * 1e6)
        queue = self.cluster.queue

        def do_restart():
            self.cluster.restart_node(victim)
            self.stats.restarts += 1
            self.restarted_nodes.append(victim)

        def do_kill():
            self.cluster.kill_node(victim)
            queue.add(down_us, do_restart)

        queue.add(0, do_kill)

    # ---------------------------------------------------- corruption arm --
    def _maybe_corrupt(self) -> None:
        """Fire the scheduled out-of-band corruption once enough ops
        completed: silently mutate one committed-below-universal command on
        a random live replica (sim/corruption.py).  Eligibility depends on
        the durability rounds having certified a window — retried on a
        virtual-time backoff until a victim txn exists."""
        if self._corrupt_at is None or self.corrupted_txn is not None:
            return
        done_ops = (self.stats.acks + self.stats.nacks + self.stats.shed
                    + self.stats.lost)
        if done_ops < self._corrupt_at:
            return
        self._corrupt_at = None  # schedule exactly one injection chain
        victim = self.rng.pick(self.cluster.live_node_ids())

        def do_corrupt():
            from accord_tpu.sim.corruption import corrupt_below_universal
            txn = corrupt_below_universal(
                self.cluster, victim,
                flip_invalidated=self._corrupt_invalidated)
            if txn is None:
                # no certified window yet: wait for a durability round
                self.cluster.queue.add(1_000_000, do_corrupt)
                return
            self.corrupted_txn = txn
            self.corrupted_node = victim

        self.cluster.queue.add(0, do_corrupt)

    # ------------------------------------------------ end-of-run auditing --
    def _run_end_audit(self) -> None:
        """The always-on audit checker: at quiesce every shard's digests
        must agree across its replicas (at whatever truncation points they
        reached), and any recorded divergence fails the burn with the
        divergent txn's stitched cross-replica flight timeline.  Rounds a
        lossy link left inconclusive are retried a few passes; live-audit
        timers are stopped first so passes do not interleave."""
        auditors = self.cluster.auditors
        for a in auditors.values():
            a.stop()
            a.census_once()
        for _attempt in range(4):
            done = {}
            for nid, a in auditors.items():
                a.audit_once(on_done=lambda r, n=nid: done.__setitem__(n, r))
            self.cluster.process_until(
                lambda: len(done) == len(auditors), max_items=2_000_000)
            reports = [r for r in done.values() if r is not None]
            outcomes = [rd["outcome"] for r in reports
                        for rd in r["rounds"]]
            if outcomes and "inconclusive" not in outcomes:
                break
        self.audit_rounds = [rd for r in reports for rd in r["rounds"]]
        divs = [d for a in auditors.values() for d in a.divergences]

        def check():
            assert not divs, (
                "audit divergence: " + "; ".join(
                    f"txn {d['txn']} {d['kind']} on range "
                    f"[{d['range'][0]},{d['range'][1]}) across nodes "
                    f"{sorted(d['nodes'])} (replicas {d['replicas']})"
                    for d in divs[:4]))

        self._with_flight_artifact(check)

    # --------------------------------------------------------------- run --
    def run(self) -> BurnStats:
        cluster = self.cluster
        submitted = [0]
        inflight = [0]
        observations = []

        def submit_one():
            if submitted[0] >= self.ops:
                return
            submitted[0] += 1
            idx = submitted[0]
            inflight[0] += 1
            txn = self._gen_txn()
            # clients only reach live nodes (a killed node's socket is gone)
            origin = self.rng.pick(cluster.live_node_ids())
            tenant = priority = ""
            if self.qos:
                tenant = f"t{self.rng.next_int(3)}"
                roll = self.rng.next_float()
                priority = ("high" if roll < 0.2
                            else "normal" if roll < 0.7 else "best_effort")
            start_us = cluster.queue.clock.now_us
            result = cluster.pipeline_submit(origin, txn, tenant, priority)

            def done(value, failure):
                from accord_tpu.pipeline.backpressure import Rejected
                from accord_tpu.qos import QosRejected
                inflight[0] -= 1
                end_us = cluster.queue.clock.now_us
                if priority:
                    cs = self.qos_class_stats.setdefault(
                        priority, {"acked": 0, "qos_shed": 0,
                                   "qos_throttle": 0, "inner_shed": 0,
                                   "failed": 0, "lost": 0})
                    if isinstance(failure, QosRejected):
                        cs["qos_" + failure.reason] += 1
                    elif isinstance(failure, Rejected):
                        cs["inner_shed"] += 1
                    elif failure is not None:
                        cs["failed"] += 1
                    elif isinstance(value, ListResult):
                        cs["acked"] += 1
                    else:
                        cs["lost"] += 1
                if isinstance(failure, Rejected):
                    # admission shed: its own summary tally (the txn was
                    # never coordinated — folding it into nacks hid every
                    # pipeline shed inside the failure count)
                    self.stats.shed += 1
                elif failure is not None:
                    self.stats.nacks += 1
                elif isinstance(value, ListResult):
                    self.stats.acks += 1
                    self.stats.ack_latencies_us.append(end_us - start_us)
                    from accord_tpu.obs.spans import trace_key
                    self._trace_of_desc[f"txn{idx}@n{origin}"] = \
                        trace_key(value.txn_id)
                    reads = {k.token: v for k, v in value.read_values.items()}
                    if isinstance(txn.keys, Ranges):
                        # a range read asserts the FULL content of the window:
                        # absent keys are an observed empty prefix (omitting a
                        # key with committed writes is a serializability bug)
                        for rng in txn.keys:
                            for token in range(rng.start, min(rng.end, self.keys)):
                                reads.setdefault(token, ())
                    observations.append(Observation(
                        f"txn{idx}@n{origin}", reads,
                        {k.token: v for k, v in value.appends.items()},
                        start_us, end_us))
                else:
                    self.stats.lost += 1
                self._maybe_kill()
                self._maybe_corrupt()
                # pipeline: keep `concurrency` txns in flight
                submit_one()

            result.add_callback(done)

        for _ in range(min(self.concurrency, self.ops)):
            submit_one()
        # predicate-driven: recurring progress-log polls keep the queue
        # non-empty forever, so "drain" means "all client ops settled" —
        # then a bounded virtual-time grace window lets trailing Apply
        # messages (and any progress-log-driven recovery) propagate
        cluster.process_until(
            lambda: submitted[0] >= self.ops and inflight[0] == 0,
            max_items=50_000_000)
        # quiesce: stop mutating topology, heal partitions, then let
        # replication/recovery drain (the reference burn similarly settles
        # before verifying)
        if self.nemesis is not None:
            self.nemesis.stop()
        if self.partition_nemesis is not None:
            self.partition_nemesis.stop()
        if self.dc_partition_nemesis is not None:
            self.dc_partition_nemesis.stop()
        if self.restarts:
            # a node may still be down (kill near the end of the run):
            # process virtual time until its scheduled restart lands —
            # verification requires every replica present
            cluster.process_until(lambda: not cluster.dead,
                                  max_items=5_000_000)
            assert not cluster.dead, "killed node never restarted"
            assert self.stats.restarts == self.restarts, \
                (self.stats.restarts, self.restarts)
        # drain trailing replication, then — because acked work may still be
        # repairing (Apply loss after long partitions; the progress-log
        # chase heals it but needs virtual time) — keep draining while
        # unapplied decided commands remain, up to a hard cap.  A REAL
        # protocol read would wait on these via deps, so verifying a raw
        # snapshot earlier would be a harness false alarm.
        for _ in range(11):
            cluster.queue.drain(
                until_us=cluster.queue.clock.now_us + 60_000_000,
                max_items=5_000_000)
            if not self._has_unapplied_decided():
                break
        self.stats.pending = inflight[0]
        tally = (self.stats.acks + self.stats.nacks + self.stats.shed
                 + self.stats.lost + self.stats.pending)
        assert tally == submitted[0], \
            f"op accounting leak: {self.stats} vs submitted={submitted[0]}"

        # always-on audit checker: cross-replica range digests must agree
        # at quiesce; divergences (e.g. the corruption arm's silent
        # mutation) fail the burn with the stitched flight timeline
        if self.audit:
            self._run_end_audit()

        # final histories: majority agreement across replicas per key
        final = self._with_flight_artifact(self._final_histories)
        if self.fault_injector is not None:
            self.fault_injector(observations)
        for obs in observations:
            self.verifier.observe(obs)
        self.verifier.verify(final)
        # journal-replay durability contract: every live command must be
        # reconstructible from the node's retained side-effecting messages
        # (SerializerSupport.reconstruct; test Journal.java:82-303)
        if self.cluster.journal is not None:
            from accord_tpu.sim.journal import validate_cluster
            self.journal_checked, self.journal_skipped = \
                self._with_flight_artifact(
                    lambda: validate_cluster(self.cluster))
        return self.stats

    # ---------------------------------------------------- observability --
    def metrics_snapshot(self) -> dict:
        """End-of-run cluster obs report (assertable in hostile tests):
        merged registries + summary (fast-path ratio, outcomes, per-phase
        latency, device flush windows, pipeline counters)."""
        return self.cluster.metrics_snapshot()

    def stitched_trace(self, trace_id: str):
        return self.cluster.stitched_trace(trace_id)

    def recovered_trace_ids(self):
        """Trace ids for which some node began a recovery coordination."""
        return self.cluster.find_trace_ids(phase="begin",
                                           path="recovery")

    # ------------------------------------------------- failure forensics --
    def flight_recorders(self):
        return self.cluster.flight_recorders()

    def stitched_flight(self, trace_ids=None, limit=None):
        return self.cluster.stitched_flight(trace_ids=trace_ids,
                                            limit=limit)

    def _forensics(self, txn_descs) -> str:
        """The verifiers' forensics hook (sim/verify.ForensicsMixin): map
        the offending observations' client descriptions to their protocol
        trace ids and stitch those transactions' flight events across every
        replica into one causally ordered timeline — leading with the first
        cross-replica status divergence when one exists."""
        from accord_tpu.obs.flight import (first_divergence, format_timeline,
                                           stitch_flight)
        tids = {self._trace_of_desc.get(d) for d in txn_descs}
        tids.discard(None)
        if not tids:
            return ""
        events = stitch_flight(self.flight_recorders(), tids, limit=400)
        self._last_forensics_events = events
        parts = []
        div = first_divergence(events)
        if div is not None:
            idx, at_i = div
            def _tr(v):
                return (f"s{v[0]}:{v[1]}->{v[2]}" if isinstance(v, tuple)
                        and len(v) == 3 else "MISSING" if v is None
                        else str(v))

            parts.append(
                f"first diverging event (status transition #{idx} "
                f"per replica): "
                + ", ".join(f"n{n}={_tr(v)}"
                            for n, v in sorted(at_i.items())))
        parts.append(format_timeline(
            events, header=f"flight timeline (cross-replica) for "
                           f"{sorted(tids)}:"))
        self.flight_artifact = "\n".join(parts)
        return self.flight_artifact

    def _with_flight_artifact(self, fn):
        """Run a verification step that has no observation context (journal
        validation, replica-divergence detection); on failure, recover the
        offending trace ids from the exception text (TxnId reprs ARE trace
        ids) — or fall back to the recent cross-replica tail — and append
        the stitched timeline to the raised error."""
        try:
            return fn()
        except AssertionError as exc:
            from accord_tpu.obs.flight import (format_timeline, stitch_flight,
                                               trace_ids_in_text)
            recorders = self.flight_recorders()
            tids = trace_ids_in_text(recorders, str(exc))
            if tids:
                events = stitch_flight(recorders, tids, limit=400)
                header = (f"flight timeline (cross-replica) for "
                          f"{sorted(tids)}:")
            else:
                events = stitch_flight(recorders, None, limit=120)
                header = ("flight timeline (cross-replica tail; no trace "
                          "ids recovered from the failure):")
            self._last_forensics_events = events
            self.flight_artifact = format_timeline(events, header=header)
            exc.args = ((f"{exc.args[0] if exc.args else exc}\n"
                         f"{self.flight_artifact}"),)
            raise

    def _has_unapplied_decided(self) -> bool:
        """Any stable-or-outcome-holding command still waiting to execute?"""
        from accord_tpu.local.status import SaveStatus
        for node in self.cluster.nodes.values():
            for store in node.command_stores.all():
                for cmd in store.commands.values():
                    if cmd.save_status in (SaveStatus.STABLE,
                                           SaveStatus.READY_TO_EXECUTE,
                                           SaveStatus.PRE_APPLIED,
                                           SaveStatus.APPLYING):
                        return True
        return False

    def _final_histories(self) -> Dict[int, Tuple[int, ...]]:
        """Longest agreed history per key across replicas (replicas may lag
        but must never diverge)."""
        cluster = self.cluster
        final: Dict[int, Tuple[int, ...]] = {}
        all_tokens = set()
        for node in cluster.nodes.values():
            all_tokens.update(node.data_store.snapshot().keys())
        for token in sorted(all_tokens):
            histories = [node.data_store.get(Key(token))
                         for node in cluster.nodes.values()]
            longest = max(histories, key=len)
            for h in histories:
                if h != longest[:len(h)]:
                    raise AssertionError(
                        f"replica divergence on key {token}: {h} vs {longest}")
            final[token] = longest
        return final


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="accord-tpu burn test")
    parser.add_argument("-s", "--seed", type=int, default=0)
    parser.add_argument("-o", "--ops", type=int, default=200)
    parser.add_argument("--nodes", type=int, default=3)
    parser.add_argument("--rf", type=int, default=None,
                        help="replication factor (< nodes = partial "
                             "replication; default full)")
    parser.add_argument("--keys", type=int, default=20)
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--drop", type=float, default=0.0)
    parser.add_argument("--partitions", action="store_true",
                        help="schedule network partitions + heals")
    parser.add_argument("--geo", action="store_true",
                        help="place nodes on the 7-node wan3 profile "
                             "(topology/geo.py: hub DC holding the slow "
                             "quorum + three single-node WAN DCs at "
                             "50/100/160ms RTT); forces --nodes 7, full "
                             "replication")
    parser.add_argument("--electorate", default=None, metavar="IDS",
                        help="--geo: comma-separated node ids forming the "
                             "fast-path electorate (default: all replicas)")
    parser.add_argument("--dc-partitions", action="store_true",
                        help="--geo: periodically sever one whole DC and "
                             "heal it (DcPartitionNemesis; "
                             "dc_partition_begin/heal flight kinds)")
    parser.add_argument("--restart", type=int, nargs="?", const=1, default=0,
                        metavar="N",
                        help="crash-restart nemesis: kill N random nodes "
                             "mid-burn (process-death semantics) and "
                             "restart each from its on-disk write-ahead "
                             "journal (accord_tpu/journal/)")
    parser.add_argument("--journal", default=None, metavar="DIR",
                        help="--restart: journal base directory (default: "
                             "a fresh temp dir)")
    parser.add_argument("--down", type=float, default=2.0,
                        help="--restart: virtual seconds a killed node "
                             "stays down before restarting")
    parser.add_argument("--drift", action="store_true",
                        help="per-node drifting wall clocks")
    parser.add_argument("--stores", type=int, default=1,
                        help="command stores per node (keyspace shards)")
    parser.add_argument("--delayed-stores", action="store_true",
                        help="run store tasks on simulated executors with "
                             "randomized delays + cache-miss page-in")
    parser.add_argument("--loops", type=int, default=1,
                        help="run N consecutive seeds")
    parser.add_argument("--device-store", action="store_true",
                        help="run deps scans on the batched device tier "
                             "(flush-window accumulation -> one kernel call)")
    parser.add_argument("--mesh-store", action="store_true",
                        help="device tier with the mesh-sharded SPMD deps "
                             "step (MeshDeviceCommandStore; needs >1 jax "
                             "device, e.g. xla_force_host_platform_"
                             "device_count)")
    parser.add_argument("--device-verify", action="store_true",
                        help="cross-check every device-served scan against "
                             "the scalar oracle inline")
    parser.add_argument("--flush-window-us", type=int, default=300,
                        help="device-store flush window (virtual us; 300 "
                             "measured best — see BASELINE.md latency-tax "
                             "table)")
    parser.add_argument("--pipeline", action="store_true",
                        help="submit through the continuous micro-batching "
                             "ingest pipeline (accord_tpu/pipeline/)")
    parser.add_argument("--qos", action="store_true",
                        help="submit through the per-tenant QoS admission "
                             "tier (accord_tpu/qos/): randomized tenants + "
                             "priority classes, deterministic pressure "
                             "shedding under virtual time")
    parser.add_argument("--range-heavy", action="store_true",
                        help="range reads ~1 in 3 ops instead of 1 in 8")
    parser.add_argument("--eph-heavy", action="store_true",
                        help="~half of ops become single-key reads on the "
                             "ephemeral (never-witnessed) read path")
    parser.add_argument("--no-audit", action="store_true",
                        help="disable the always-on end-of-run replica-"
                             "state audit checker (local/audit.py)")
    parser.add_argument("--audit-live", type=float, default=0.0,
                        metavar="S",
                        help="run the periodic live audit+census every S "
                             "virtual seconds during the burn (the "
                             "production cadence; 0 = end-of-run only)")
    parser.add_argument("--corrupt", type=int, nargs="?", const=0,
                        default=None, metavar="N",
                        help="corruption nemesis: after N completed ops "
                             "(default ops/2) silently mutate one "
                             "committed command on a random replica — the "
                             "audit checker must then FAIL the burn "
                             "naming the divergent txn")
    parser.add_argument("--message-stats", action="store_true",
                        help="print per-message-type delivery/drop counters")
    parser.add_argument("--trace", action="store_true",
                        help="record structured protocol events per node and "
                             "print the tail after the run")
    parser.add_argument("--metrics", action="store_true",
                        help="print the end-of-run obs report (merged "
                             "metrics registry summary, JSON)")
    parser.add_argument("--cpu-top", action="store_true",
                        help="print the merged protocol-CPU waterfall "
                             "(per-verb stage p50/p99 + top-verbs table, "
                             "obs/cpuprof.py; set ACCORD_CPU_PROFILE=N "
                             "to sample, else the section is empty)")
    parser.add_argument("--flight-dump", action="store_true",
                        help="print the stitched cross-replica flight-"
                             "recorder tail after the run (the same view "
                             "the failure artifact captures)")
    parser.add_argument("--flight-txn", default=None,
                        help="--flight-dump: filter to trace ids containing "
                             "this substring")
    args = parser.parse_args(argv)
    if args.device_store or args.mesh_store:
        # the device store initialises jax: probe the (possibly
        # dead-tunneled) TPU backend with a timeout first, falling back to
        # CPU, or the CLI blocks forever on backend resolution
        from accord_tpu.utils.backend import resolve_platform
        resolve_platform()

    def make_store_factory(seed: int):
        # built PER SEED: a shared delayed-store RandomSource would carry
        # its state across --loops iterations, making a failure at loop
        # seed N irreproducible by `-s N` alone (burn soaks found exactly
        # that: a seed-15003 violation that vanished standalone)
        if args.device_store or args.mesh_store:
            if args.delayed_stores:
                # delayed-executor nemesis composed OVER the device tier
                from accord_tpu.sim.delayed_store import delayed_device_factory
                from accord_tpu.utils.random_source import RandomSource
                return delayed_device_factory(
                    RandomSource(seed ^ 0x5D5D), mesh_store=args.mesh_store,
                    flush_window_us=args.flush_window_us,
                    verify=args.device_verify)
            if args.mesh_store:
                from accord_tpu.impl.device_store import MeshDeviceCommandStore
                return MeshDeviceCommandStore.factory(
                    flush_window_us=args.flush_window_us,
                    verify=args.device_verify)
            from accord_tpu.impl.device_store import DeviceCommandStore
            return DeviceCommandStore.factory(
                flush_window_us=args.flush_window_us,
                verify=args.device_verify)
        if args.delayed_stores:
            from accord_tpu.sim.delayed_store import DelayedCommandStore
            from accord_tpu.utils.random_source import RandomSource
            return DelayedCommandStore.factory(RandomSource(seed ^ 0x5D5D))
        return None

    geo = None
    electorate = None
    if args.geo:
        from accord_tpu.topology.geo import wan3_profile
        geo = wan3_profile()
        args.nodes = len(geo.node_dc)
        args.rf = None  # full replication: every shard spans every DC
        if args.electorate:
            electorate = frozenset(
                int(t) for t in args.electorate.split(","))
    for i in range(args.loops):
        seed = args.seed + i
        store_factory = make_store_factory(seed)
        # one journal world per seed: reusing a directory across loops
        # would replay seed N's history into seed N+1's cluster
        journal_dir = (None if args.journal is None
                       else f"{args.journal}/seed-{seed}")
        run = BurnRun(seed, args.ops, nodes=args.nodes, keys=args.keys,
                      rf=args.rf, range_every=3 if args.range_heavy else 8,
                      n_shards=args.shards, drop_prob=args.drop,
                      store_factory=store_factory,
                      num_command_stores=args.stores,
                      partitions=args.partitions, clock_drift=args.drift,
                      trace=args.trace, pipeline=args.pipeline,
                      qos=args.qos,
                      restarts=args.restart, journal_dir=journal_dir,
                      restart_down_s=args.down,
                      eph_ratio=0.5 if args.eph_heavy else 0.0,
                      audit=not args.no_audit,
                      audit_live_s=args.audit_live,
                      census_live_s=args.audit_live,
                      corrupt_at=(None if args.corrupt is None
                                  else (args.corrupt or args.ops // 2)),
                      geo=geo, electorate=electorate,
                      dc_partitions=args.dc_partitions)
        stats = run.run()
        if args.trace:
            for node in run.cluster.nodes.values():
                dump = node.trace.dump(limit=40)
                if dump:
                    print(dump)
        extra = ""
        if args.device_store or args.mesh_store:
            h = m = b = p = rh = rm = dis = 0
            wb = wp = wx = wd = gh = gm = 0
            mx = xw = 0
            for node in run.cluster.nodes.values():
                for s in node.command_stores.all():
                    h += s.device_hits
                    m += s.device_misses
                    b += s.device_batches
                    p += s.device_batched_probes
                    mx = max(mx, s.device_max_batch)
                    rh += s.device_recovery_hits
                    rm += s.device_recovery_misses
                    wb += s.device_wave_batches
                    wp += s.device_wave_planned
                    wx += s.device_wave_executed
                    wd = max(wd, s.device_wave_max_depth)
                    gh += s.device_range_hits
                    gm += s.device_range_misses
                    xw += s.device_cross_txn_windows
                    dis += s.device_disabled
            extra = (f" device[hits={h} misses={m} batches={b} "
                     f"probes={p} max_batch={mx} cross_txn_windows={xw} "
                     f"recovery_hits={rh} recovery_misses={rm} "
                     f"wave_batches={wb} wave_planned={wp} "
                     f"wave_executed={wx} wave_depth={wd} "
                     f"range_hits={gh} range_misses={gm}"
                     + (f" DISABLED={dis}" if dis else "") + "]")
        if run.cluster.pipelines:
            ps = [p.stats for p in run.cluster.pipelines.values()]
            extra += (f" pipeline[batches={sum(s.batches for s in ps)} "
                      f"dispatched={sum(s.dispatched for s in ps)} "
                      f"shed={sum(s.shed for s in ps)} "
                      f"batch_max={max(s.batch_size_max for s in ps)} "
                      f"batch_mean="
                      f"{sum(s.dispatched for s in ps) / max(1, sum(s.batches for s in ps)):.1f}]")
        if run.qos_class_stats:
            parts = []
            for pr in ("high", "normal", "best_effort"):
                cs = run.qos_class_stats.get(pr)
                if cs:
                    parts.append(f"{pr}={cs['acked']}a/{cs['qos_shed']}s/"
                                 f"{cs['qos_throttle']}t/{cs['inner_shed']}i")
            extra += " qos[" + " ".join(parts) + "]"
        inf = {"evidence": 0, "quorum_evidence": 0, "inferred_rounds": 0,
               "no_round_commits": 0, "fence_refusals": 0,
               "safe_to_clean": 0}
        for node in run.cluster.nodes.values():
            for k in inf:
                inf[k] += node.infer_stats[k]
        if any(inf.values()):
            # the Infer ladder A/B (coordinate/infer.py): quorum_evidence
            # counts interrogations resolvable with no extra round;
            # no_round_commits is how many the full ladder settled that
            # way; inferred_rounds is what was still paid in
            # ballot-protected Invalidate rounds (sub-quorum evidence or
            # the ACCORD_INFER_FULL=0 escape hatch)
            extra += (f" infer[evidence={inf['evidence']} "
                      f"quorum_evidence={inf['quorum_evidence']} "
                      f"inferred_rounds={inf['inferred_rounds']} "
                      f"no_round={inf['no_round_commits']} "
                      f"fence_refusals={inf['fence_refusals']} "
                      f"safe_to_clean={inf['safe_to_clean']}]")

        if run.audit_rounds:
            agree = sum(1 for r in run.audit_rounds
                        if r["outcome"] == "agree")
            extra += (f" audit[rounds={len(run.audit_rounds)} "
                      f"agree={agree}]")
        if run.dc_partition_nemesis is not None:
            extra += (f" dc_partitions["
                      f"{run.dc_partition_nemesis.partitions_applied}]")

        def lat(pct):
            us = stats.latency_us(pct)
            return f"{us / 1e3:.1f}ms" if us >= 0 else "n/a"

        print(f"seed={seed} ops={args.ops} {stats} "
              f"lat_p50={lat(50)} lat_p95={lat(95)} "
              f"virtual_time={run.cluster.now_s:.1f}s "
              f"events={run.cluster.queue.processed} OK{extra}")
        if args.metrics:
            import json as _json
            print("obs " + _json.dumps(run.metrics_snapshot()["summary"]))
        if args.cpu_top:
            import json as _json
            print("cpu " + _json.dumps(
                run.metrics_snapshot()["summary"]["cpu"]))
        if args.flight_dump:
            from accord_tpu.obs.flight import format_timeline
            tids = None
            if args.flight_txn:
                tids = {t for rec in run.flight_recorders()
                        for t in rec.trace_ids() if args.flight_txn in t}
            print(format_timeline(
                run.stitched_flight(trace_ids=tids, limit=120),
                header="flight (cross-replica tail):"))
        if args.message_stats:
            # per-verb delivery/drop counters (reference burn reports
            # messageStatsMap per message type, BurnTest.java:510+)
            net = run.cluster.network.stats
            verbs = sorted({k.split(".", 1)[1] for k in net})
            for verb in verbs:
                d = net.get(f"deliver.{verb}", 0)
                x = net.get(f"drop.{verb}", 0)
                print(f"  {verb:<28} delivered={d:<7} dropped={x}")
        if stats.acks == 0:
            print("PATHOLOGICAL: no transaction succeeded", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
