"""Delayed command stores: the storage/executor nemesis.

Reference: accord-core test impl/basic/DelayedCommandStores.java:61-175 —
every store task goes through a simulated single-threaded executor with
randomized delays, plus a random isLoadedCheck that models async cache-miss
page-in of the PreLoadContext. Exercises every path that assumes store
operations complete inline: callbacks must tolerate arbitrary interleaving
of store execution with message delivery and timer events.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

from accord_tpu.local.store import CommandStore, PreLoadContext
from accord_tpu.utils.random_source import RandomSource


class DelayedCommandStore(CommandStore):
    """CommandStore whose tasks run on a simulated executor: submissions
    queue; each drains after a randomized delay, sequentially (the store
    stays logically single-threaded — delays reorder store work relative to
    network/timer events, never relative to other tasks on the same store).

    `miss_prob` adds an extra page-in delay to a task whose PreLoadContext
    names commands/keys, modelling the async cache-miss path."""

    def __init__(self, store_id: int, node, ranges, *,
                 random: RandomSource,
                 min_delay_us: int = 50, max_delay_us: int = 2_000,
                 miss_prob: float = 0.2, miss_delay_us: int = 5_000,
                 **base_kw):
        # **base_kw flows to the next class in the MRO so the delay nemesis
        # composes over richer store tiers (device/mesh flush-window stores)
        super().__init__(store_id, node, ranges, **base_kw)
        self.random = random
        self.min_delay_us = min_delay_us
        self.max_delay_us = max_delay_us
        self.miss_prob = miss_prob
        self.miss_delay_us = miss_delay_us
        self._tasks = deque()
        self._draining = False
        self.tasks_run = 0
        self.misses_simulated = 0

    @classmethod
    def factory(cls, random: RandomSource, **kw):
        """One forked RandomSource per store keeps runs seed-deterministic."""
        return lambda i, node, ranges: cls(i, node, ranges,
                                           random=random.fork(), **kw)

    def _submit(self, context: PreLoadContext, fn, result) -> None:
        self._tasks.append((context, fn, result))
        if not self._draining:
            self._draining = True
            self._schedule_next()

    def _task_delay(self, context: PreLoadContext) -> int:
        delay = self.random.next_int(self.min_delay_us, self.max_delay_us)
        if (context.txn_ids or len(context.keys) > 0) \
                and self.random.next_float() < self.miss_prob:
            # async cache miss: the store must page the context in first
            self.misses_simulated += 1
            delay += self.random.next_int(1, self.miss_delay_us)
        return delay

    def _schedule_next(self) -> None:
        context = self._tasks[0][0]
        self.node.scheduler.once(self._task_delay(context) / 1e6, self._drain_one)

    def _drain_one(self) -> None:
        context, fn, result = self._tasks.popleft()
        self.tasks_run += 1
        try:
            super()._submit(context, fn, result)
        finally:
            if self._tasks:
                self._schedule_next()
            else:
                self._draining = False


def _device_bases():
    # lazy: pulls numpy/jax-adjacent modules only when a device-tier burn
    # actually asks for the composition
    from accord_tpu.impl.device_store import (DeviceCommandStore,
                                              MeshDeviceCommandStore,
                                              _mesh_step_setup)
    return DeviceCommandStore, MeshDeviceCommandStore, _mesh_step_setup


def delayed_device_factory(random: RandomSource, *, mesh_store: bool = False,
                           flush_window_us: int = 0, verify: bool = False):
    """Store factory composing the delayed-executor nemesis over the batched
    device tier (reference analogue: DelayedCommandStores.java:61-175
    wrapping the real store): tasks queue on the simulated delayed executor
    with randomized delays + cache-miss page-ins, then drain into the device
    store's flush window, exercising the batch path under storage-latency
    chaos.  `mesh_store` selects the mesh-sharded SPMD tier."""
    DeviceCommandStore, MeshDeviceCommandStore, _mesh_step_setup = \
        _device_bases()

    class DelayedDeviceCommandStore(DelayedCommandStore, DeviceCommandStore):
        pass

    class DelayedMeshDeviceCommandStore(DelayedCommandStore,
                                        MeshDeviceCommandStore):
        pass

    if mesh_store:
        mesh, step, n_shards = _mesh_step_setup(None)
        return lambda i, node, ranges: DelayedMeshDeviceCommandStore(
            i, node, ranges, random=random.fork(),
            flush_window_us=flush_window_us, verify=verify,
            mesh=mesh, sharded_step=step, n_shards=n_shards)
    return lambda i, node, ranges: DelayedDeviceCommandStore(
        i, node, ranges, random=random.fork(),
        flush_window_us=flush_window_us, verify=verify)
