"""Third, independently-authored history checker: a port of Elle's
list-append analysis.

The reference composes its own strict-serializability verifier with Elle,
jepsen's community-hardened checker (accord-core/build.gradle:36-46,
test/accord/verify/ElleVerifier.java:47).  Rounds 1-3 composed two
home-grown algorithms written against one author's mental model; this
module de-correlates the oracle by porting the PUBLISHED algorithm from
Elle's paper (Kingsbury & Alvaro, "Elle: Inferring Isolation Anomalies
from Experimental Observations", VLDB 2020) for the list-append workload:

  1. VERSION ORDERS are inferred from the observations themselves — every
     read of a key is a version of its list, and list-append's prefix
     property requires all observed versions of a key to form a chain
     under the prefix relation ("incompatible order" anomaly otherwise).
     The final history joins as the closing read.
  2. DIRTY/ABORTED READS (G1a): a read strictly longer than the final
     history means values surfaced to a reader but never durably
     happened.
  3. DEPENDENCY EDGES are derived per Elle's recoverability argument:
       wr: T2 read a version whose last element T1 appended;
       ww: T1 appended the element immediately preceding T2's append in
           the inferred version order;
       rw: T1 read a version that T2's append immediately extends.
  4. REAL-TIME edges join for strict serializability (Elle's "realtime"
     graph under Jepsen).
  5. CYCLE SEARCH runs Tarjan's strongly-connected-components algorithm;
     a non-trivial SCC is an anomaly, CLASSIFIED by the edge kinds on a
     concrete cycle recovered from the SCC: G0 (write cycle), G1c (ww+wr),
     G-single (exactly one rw), G2 (multiple rw), with "-realtime"
     appended when real-time edges participate.

Structural independence from the two in-tree checkers: sim/verify.py
tests one constraint graph for acyclicity via Kahn counting; verify_replay
constructs an explicit witness and replays it against a model store;
this checker infers version orders purely from reads, computes SCCs, and
names the anomaly class.  All three must pass on every burn.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from accord_tpu.sim.verify import (ForensicsMixin, Observation, Violation,
                                   real_time_edges)

WW, WR, RW, RT = "ww", "wr", "rw", "realtime"


class ElleListAppendChecker(ForensicsMixin):
    """Same observe/verify surface as the other two checkers."""

    def __init__(self):
        self.observations: List[Observation] = []

    def observe(self, obs: Observation) -> None:
        self.observations.append(obs)

    # ---------------------------------------------------------- verify --
    def verify(self, final_histories: Dict[int, Sequence[int]]) -> None:
        obs = self.observations
        n = len(obs)

        # -- step 1: per-key version chains from reads + final history --
        versions: Dict[int, List[Tuple[int, ...]]] = {}
        for o in obs:
            for token, read in o.reads.items():
                versions.setdefault(token, []).append(tuple(read))
        for token, hist in final_histories.items():
            versions.setdefault(token, []).append(tuple(hist))
        order: Dict[int, Tuple[int, ...]] = {}
        for token, vs in versions.items():
            vs.sort(key=len)
            for a, b in zip(vs, vs[1:]):
                if b[:len(a)] != a:
                    raise Violation(
                        f"elle: incompatible version order on key {token}: "
                        f"{a} vs {b} (no prefix chain)")
            # the final history is one of the versions; a longer READ means
            # observed appends vanished from the final state (G1a-class:
            # values surfaced to a reader but never durably happened)
            final = tuple(final_histories.get(token, ()))
            if vs and len(vs[-1]) > len(final):
                raise Violation(
                    f"elle: G1a — key {token} was read as {vs[-1]} but "
                    f"finally holds only {final}: observed appends vanished")
            order[token] = vs[-1] if vs else ()

        # appender of each (token, value); duplicate appends of one value
        # would corrupt recoverability, and an ACKED append absent from
        # the inferred version order is Elle's lost-update anomaly
        appender: Dict[Tuple[int, int], int] = {}
        for i, o in enumerate(obs):
            for token, value in o.appends.items():
                if (token, value) in appender:
                    raise Violation(
                        f"elle: value {value} appended to key {token} twice")
                appender[(token, value)] = i
                if value not in order.get(token, ()):
                    raise self._violation(
                        f"elle: lost update — acked append of {value} to "
                        f"key {token} is absent from the version order "
                        f"{order.get(token, ())} ({o})",
                        txn_descs=[o.txn_desc])

        # -- step 3+4: dependency edges (parallel adjacency by kind) --
        # node ids: 0..n-1 observations; values appended by no observed
        # txn (committed-but-unobserved winners) get phantom nodes
        phantom_of: Dict[Tuple[int, int], int] = {}
        labels: List[object] = [o.txn_desc for o in obs]

        def writer(token: int, value: int) -> int:
            i = appender.get((token, value))
            if i is not None:
                return i
            key = (token, value)
            if key not in phantom_of:
                phantom_of[key] = len(labels)
                labels.append(f"phantom({token}={value})")
            return phantom_of[key]

        edges: Dict[Tuple[int, int], Set[str]] = {}

        def edge(a: int, b: int, kind: str) -> None:
            if a != b:
                edges.setdefault((a, b), set()).add(kind)

        for token, version in order.items():
            for p in range(1, len(version)):
                edge(writer(token, version[p - 1]),
                     writer(token, version[p]), WW)
        for i, o in enumerate(obs):
            for token, read in o.reads.items():
                version = order.get(token, ())
                if read:
                    edge(writer(token, read[-1]), i, WR)
                if len(read) < len(version):
                    edge(i, writer(token, version[len(read)]), RW)
        real_time_edges(obs, lambda a, b: edge(a, b, RT))

        total = len(labels)
        succ: List[List[int]] = [[] for _ in range(total)]
        for (a, b) in edges:
            succ[a].append(b)

        # -- step 5: Tarjan SCC (iterative), then classify a cycle --
        sccs = _tarjan(total, succ)
        for scc in sccs:
            if len(scc) < 2:
                continue
            cycle = _find_cycle(scc, succ)
            kinds: Set[str] = set()
            for a, b in zip(cycle, cycle[1:] + cycle[:1]):
                kinds |= edges.get((a, b), set())
            raise self._violation(
                f"elle: {_classify(kinds, edges, cycle)} cycle over "
                f"{[labels[i] for i in cycle]}",
                txn_descs=[labels[i] for i in cycle
                           if isinstance(labels[i], str)
                           and not labels[i].startswith("phantom(")])

    # introspection for tests: the checker found the history clean
    def __repr__(self):
        return f"ElleListAppendChecker({len(self.observations)} obs)"


def _classify(kinds: Set[str], edges, cycle: List[int]) -> str:
    rw_count = 0
    for a, b in zip(cycle, cycle[1:] + cycle[:1]):
        if RW in edges.get((a, b), set()) \
                and not (edges.get((a, b), set()) - {RW, RT}):
            rw_count += 1
    data = kinds - {RT}
    if data <= {WW}:
        name = "G0"
    elif data <= {WW, WR}:
        name = "G1c"
    elif rw_count == 1:
        name = "G-single"
    else:
        name = "G2"
    return name + ("-realtime" if RT in kinds else "")


def _tarjan(n: int, succ: List[List[int]]) -> List[List[int]]:
    """Iterative Tarjan strongly-connected components."""
    index = [0] * n
    low = [0] * n
    on_stack = [False] * n
    visited = [False] * n
    stack: List[int] = []
    out: List[List[int]] = []
    counter = [1]
    for root in range(n):
        if visited[root]:
            continue
        work = [(root, 0)]
        while work:
            v, pi = work[-1]
            if pi == 0:
                visited[v] = True
                index[v] = low[v] = counter[0]
                counter[0] += 1
                stack.append(v)
                on_stack[v] = True
            recurse = False
            for j in range(pi, len(succ[v])):
                w = succ[v][j]
                if not visited[w]:
                    work[-1] = (v, j + 1)
                    work.append((w, 0))
                    recurse = True
                    break
                if on_stack[w]:
                    low[v] = min(low[v], index[w])
            if recurse:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack[w] = False
                    comp.append(w)
                    if w == v:
                        break
                out.append(comp)
    return out


def _find_cycle(scc: List[int], succ: List[List[int]]) -> List[int]:
    """A concrete cycle inside a non-trivial SCC: BFS from its first node
    back to itself through SCC-internal edges."""
    members = set(scc)
    start = scc[0]
    parent: Dict[int, int] = {}
    frontier = [start]
    while frontier:
        nxt = []
        for v in frontier:
            for w in succ[v]:
                if w == start:
                    path = [v]
                    while path[-1] != start:
                        path.append(parent[path[-1]])
                    path.reverse()
                    return path
                if w in members and w not in parent:
                    parent[w] = v
                    nxt.append(w)
        frontier = nxt
    return [start]  # unreachable for a genuine SCC
