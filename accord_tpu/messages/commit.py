"""Commit / Stable: fix (executeAt, deps) — optionally piggybacking the read.

Reference: accord/messages/Commit.java:61 — Kinds CommitSlowPath/CommitMaximal/
StableFastPath/StableSlowPath/StableMaximal (:84-96); `stableAndRead`
piggybacks ReadTxnData onto Stable for read-set members (:175); inner
Commit.Invalidate.
"""

from __future__ import annotations

import enum
from typing import Optional

from accord_tpu.local import commands as C
from accord_tpu.messages.base import MessageType, Reply, Request, SimpleReply, TxnRequest
from accord_tpu.messages.read import execute_read_when_ready
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Keys, Route
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnId
from accord_tpu.primitives.txn import PartialTxn
from accord_tpu.utils.async_chains import AsyncResult, success


class CommitKind(enum.Enum):
    COMMIT_SLOW_PATH = MessageType.COMMIT_SLOW_PATH_REQ
    COMMIT_MAXIMAL = MessageType.COMMIT_MAXIMAL_REQ
    STABLE_FAST_PATH = MessageType.STABLE_FAST_PATH_REQ
    STABLE_SLOW_PATH = MessageType.STABLE_SLOW_PATH_REQ
    STABLE_MAXIMAL = MessageType.STABLE_MAXIMAL_REQ

    @property
    def is_stable(self) -> bool:
        return self in (CommitKind.STABLE_FAST_PATH, CommitKind.STABLE_SLOW_PATH,
                        CommitKind.STABLE_MAXIMAL)


class Commit(TxnRequest):
    def __init__(self, kind: CommitKind, txn_id: TxnId, scope: Route,
                 partial_txn: Optional[PartialTxn], execute_at: Timestamp,
                 deps: Deps, read_keys: Optional[Keys] = None,
                 full_route: Route = None):
        super().__init__(txn_id, scope, wait_for_epoch=execute_at.epoch,
                         full_route=full_route)
        self.kind = kind
        self.type = kind.value
        self.partial_txn = partial_txn
        self.execute_at = execute_at
        self.deps = deps
        self.read_keys = read_keys  # non-None: stableAndRead piggyback

    def apply(self, safe_store):
        outcome = C.commit(
            safe_store, self.txn_id, self.route, self.partial_txn,
            self.execute_at, self.deps.slice(safe_store.ranges)
            if not safe_store.ranges.is_empty else self.deps,
            stable=self.kind.is_stable)
        if outcome == C.AcceptOutcome.TRUNCATED:
            return SimpleReply(SimpleReply.NACK)
        if self.read_keys is not None and self.kind.is_stable:
            return execute_read_when_ready(safe_store, self.txn_id,
                                           self.read_keys)
        return SimpleReply(SimpleReply.OK)

    def reduce(self, a, b):
        from accord_tpu.messages.read import ReadNack, ReadOk
        if isinstance(a, ReadNack):
            return a
        if isinstance(b, ReadNack):
            return b
        if isinstance(a, ReadOk) and isinstance(b, ReadOk):
            return a.merge(b)
        if isinstance(a, ReadOk):
            return a
        if isinstance(b, ReadOk):
            return b
        if isinstance(a, SimpleReply) and a.outcome == SimpleReply.NACK:
            return a
        return b

    def __repr__(self):
        return f"Commit({self.kind.name}, {self.txn_id!r}@{self.execute_at!r})"


class CommitInvalidate(TxnRequest):
    type = MessageType.COMMIT_INVALIDATE_REQ

    def __init__(self, txn_id: TxnId, scope: Route):
        super().__init__(txn_id, scope)

    def apply(self, safe_store):
        C.commit_invalidate(safe_store, self.txn_id)
        return SimpleReply(SimpleReply.OK)

    def reduce(self, a, b):
        return b
