"""GetMaxConflict: query the highest conflicting timestamp over a selection.

Reference: accord/messages/GetMaxConflict.java — a txn-less TxnRequest that
map-reduces `MaxConflicts` over the receiving node's command stores and
reports the store's view of the latest epoch, so the coordinator
(coordinate/fetch.fetch_max_conflict, reference FetchMaxConflict.java) can
chase topology changes that race with the query.
"""

from __future__ import annotations

from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.primitives.keys import Route
from accord_tpu.primitives.timestamp import NONE as TS_NONE
from accord_tpu.primitives.timestamp import TXNID_NONE, Timestamp


class GetMaxConflict(TxnRequest):
    """Ask each replica for max(MaxConflicts) over `participants`
    (GetMaxConflict.java:35-85)."""

    type = MessageType.GET_MAX_CONFLICT_REQ

    def __init__(self, scope: Route, participants, execution_epoch: int):
        super().__init__(TXNID_NONE, scope, wait_for_epoch=execution_epoch,
                         min_epoch=execution_epoch)
        # Keys or Ranges, pre-sliced to the destination's scope
        self.query_participants = participants
        self.execution_epoch = execution_epoch

    def apply(self, safe_store) -> "GetMaxConflictOk":
        mc = safe_store.max_conflict(self.query_participants)
        return GetMaxConflictOk(mc if mc is not None else TS_NONE,
                                max(safe_store.node.epoch,
                                    self.execution_epoch))

    def reduce(self, a: "GetMaxConflictOk", b: "GetMaxConflictOk"
               ) -> "GetMaxConflictOk":
        return GetMaxConflictOk(max(a.max_conflict, b.max_conflict),
                                max(a.latest_epoch, b.latest_epoch))

    def __repr__(self):
        return (f"GetMaxConflict({self.query_participants!r}, "
                f"epoch={self.execution_epoch})")


class GetMaxConflictOk(Reply):
    type = MessageType.GET_MAX_CONFLICT_RSP

    __slots__ = ("max_conflict", "latest_epoch")

    def __init__(self, max_conflict: Timestamp, latest_epoch: int):
        self.max_conflict = max_conflict
        self.latest_epoch = latest_epoch

    def __repr__(self):
        return f"GetMaxConflictOk({self.max_conflict!r}, e={self.latest_epoch})"
