"""Replica-state audit verbs: cross-replica range digests + drill-down.

No reference counterpart — the reference's correctness story is offline
(burn checkers, Elle); these verbs are the ONLINE verification surface the
production host needs (ISSUE 7): an auditor node periodically asks every
replica of a range for an order-insensitive digest of its decided command
state, bounded by the negotiated cleanup watermarks so replicas at
different truncation points still agree; a mismatch drills down (bisecting
by txn-id window) to per-transaction entry lists and the first divergent
transaction.

All verbs are READ-ONLY (has_side_effects=False — never journaled) and
deliberately NOT TxnRequests: a digest walk is a node-level fold with
cross-store dedup (one leaf per transaction however its keys shard), so
`process` computes directly over the node's stores instead of the per-store
map-reduce.  The walks themselves live in local/audit.py.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from accord_tpu.messages.base import MessageType, Reply, Request
from accord_tpu.primitives.keys import Ranges
from accord_tpu.primitives.timestamp import Timestamp


class AuditDigestOk(Reply):
    """One replica's digest of its decided command state over the audited
    ranges within [lo, hi).

    digest     — hex of the 128-bit XOR fold of per-txn leaves
                 (local/audit.entry_leaf over canonical wire packings)
    count      — transactions folded in
    lo_floor   — this replica's bootstrap/staleness low bound for the
                 ranges (digests must not reach below it)
    hi_floor   — this replica's universal-durable floor (above it this
                 replica is not yet certified to hold everything)
    """

    type = MessageType.AUDIT_DIGEST_RSP

    def __init__(self, digest: str, count: int, lo_floor: Timestamp,
                 hi_floor: Timestamp):
        self.digest = digest
        self.count = count
        self.lo_floor = lo_floor
        self.hi_floor = hi_floor

    def __repr__(self):
        return (f"AuditDigestOk({self.digest[:12]}.. n={self.count} "
                f"lo={self.lo_floor!r} hi={self.hi_floor!r})")


class AuditDigest(Request):
    """Fold decided command state for `ranges` within [lo, hi) into one
    order-insensitive digest (AUDIT_DIGEST_REQ)."""

    type = MessageType.AUDIT_DIGEST_REQ

    def __init__(self, ranges: Ranges, lo: Timestamp, hi: Timestamp):
        self.ranges = ranges
        self.lo = lo
        self.hi = hi

    def process(self, node, from_id: int, reply_context) -> None:
        if node.command_stores.remote:
            # worker runtime: the stores live in per-shard processes — fan
            # the walk out over the worker pipes and merge (supervisor.py)
            node.command_stores.audit_request(self, from_id, reply_context)
            return
        from accord_tpu.local import audit as A
        node.reply(from_id, reply_context,
                   A.digest_reply(node, self.ranges, self.lo, self.hi))

    def __repr__(self):
        return f"AuditDigest({self.ranges!r} [{self.lo!r}, {self.hi!r}))"


class AuditEntriesOk(Reply):
    """Drill-down entry list: (txn_id, cls, execute_at) per decided txn in
    the window, cls in ("committed", "invalidated", "unknown")."""

    type = MessageType.AUDIT_ENTRIES_RSP

    def __init__(self, entries: Tuple[tuple, ...], truncated: bool = False):
        self.entries = tuple(entries)
        # True when the reply was cut at the serving limit — the auditor
        # must bisect further instead of trusting a partial diff
        self.truncated = truncated

    def __repr__(self):
        return (f"AuditEntriesOk(n={len(self.entries)}"
                + (", truncated" if self.truncated else "") + ")")


class AuditEntries(Request):
    """Fetch the per-transaction entries backing a digest window
    (AUDIT_ENTRIES_REQ) — sent only after a digest mismatch, on a window
    bisected small enough to enumerate."""

    type = MessageType.AUDIT_ENTRIES_REQ

    # serving cap: a drill-down that still exceeds this is answered
    # truncated, forcing the auditor to keep bisecting
    LIMIT = 4096

    def __init__(self, ranges: Ranges, lo: Timestamp, hi: Timestamp,
                 limit: Optional[int] = None):
        self.ranges = ranges
        self.lo = lo
        self.hi = hi
        self.limit = limit if limit is not None else self.LIMIT

    def process(self, node, from_id: int, reply_context) -> None:
        if node.command_stores.remote:
            node.command_stores.audit_request(self, from_id, reply_context)
            return
        from accord_tpu.local import audit as A
        entries = A.collect_entries(node, self.ranges, self.lo, self.hi)
        limit = min(self.limit, self.LIMIT)
        truncated = len(entries) > limit
        node.reply(from_id, reply_context,
                   AuditEntriesOk(tuple(entries[:limit]), truncated))

    def __repr__(self):
        return f"AuditEntries({self.ranges!r} [{self.lo!r}, {self.hi!r}))"
