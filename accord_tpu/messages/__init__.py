"""Wire protocol (reference: accord/messages — SURVEY.md §2.4)."""

from accord_tpu.messages.base import (
    MessageType, Request, Reply, TxnRequest, Callback, SimpleReply, FailureReply,
)
from accord_tpu.messages.preaccept import PreAccept, PreAcceptOk, PreAcceptNack
from accord_tpu.messages.accept import Accept, AcceptOk, AcceptNack
from accord_tpu.messages.commit import Commit, CommitInvalidate
from accord_tpu.messages.apply_msg import Apply, ApplyReply
from accord_tpu.messages.invalidate_msg import BeginInvalidation, InvalidateReply
from accord_tpu.messages.multi import MultiPreAccept
from accord_tpu.messages.read import ReadTxnData, ReadOk, ReadNack
