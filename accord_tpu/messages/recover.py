"""BeginRecovery: the recovery voting round.

Reference: accord/messages/BeginRecovery.java:55 — per-shard Commands.recover
(ballot gate) then the fast-path-decipher predicates via mapReduceFull
(:104-190); RecoverOk carries {status, accepted ballot, executeAt, deps,
earlierCommittedWitness, earlierAcceptedNoWitness, rejectsFastPath, writes,
result}; RecoverNack carries the superseding promise.
"""

from __future__ import annotations

from typing import Optional

from accord_tpu.local import commands as C
from accord_tpu.local.status import InvalidIf, KnownDeps, SaveStatus
from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Key, Keys, Route
from accord_tpu.primitives.latest_deps import LatestDeps
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnId
from accord_tpu.primitives.txn import PartialTxn
from accord_tpu.primitives.writes import Writes


class RecoverOk(Reply):
    type = MessageType.BEGIN_RECOVER_RSP

    def __init__(self, txn_id: TxnId, status: SaveStatus,
                 accepted_ballot: Ballot, execute_at: Optional[Timestamp],
                 latest_deps: LatestDeps, partial_txn: Optional[PartialTxn],
                 writes: Optional[Writes], result,
                 rejects_fast_path: bool,
                 earlier_committed_witness: Deps,
                 earlier_no_witness: Deps,
                 unresolved_covers: Deps = Deps.NONE,
                 invalid_if: InvalidIf = InvalidIf.NOT_KNOWN_TO_BE_INVALID):
        self.txn_id = txn_id
        self.status = status
        self.accepted_ballot = accepted_ballot
        self.execute_at = execute_at
        # per-range KnownDeps-aware deps knowledge: local PreAccept-style
        # calculations, Accept-round proposals with their ballots, and
        # committed deps, merged range-wise across the quorum
        self.latest_deps = latest_deps
        self.partial_txn = partial_txn
        self.writes = writes
        self.result = result
        self.rejects_fast_path = rejects_fast_path
        self.earlier_committed_witness = earlier_committed_witness
        self.earlier_no_witness = earlier_no_witness
        # write deps whose undecided commit status makes this replica's
        # omission evidence inconclusive (CommandsForKey.omission_covers):
        # the coordinator must await their commit and retry before reading
        # the fast-path decipher either way
        self.unresolved_covers = unresolved_covers
        # durability-derived invalidation evidence (coordinate/infer.py):
        # the strongest InvalidIf condition this replica's watermarks
        # justify over the queried participants, attached only when the
        # txn is locally undecided.  A per-shard quorum of these lets the
        # recovering coordinator commit invalidation off its own promise
        # round, skipping the ProposeInvalidate round entirely
        self.invalid_if = invalid_if

    @property
    def witnessed_at_original(self) -> bool:
        """Could this replica have cast a fast-path vote in the PreAccept
        round? True iff it had witnessed the txn at its original timestamp."""
        return self.execute_at is not None \
            and self.execute_at == self.txn_id.as_timestamp()

    def _rank(self):
        """Cross-reply ranking key (reference Status.max over phase +
        acceptedOrCommitted ballot): ACCEPTED and ACCEPTED_INVALIDATE are
        the SAME phase and compete by BALLOT — a higher-ballot promise to
        invalidate supersedes a lower-ballot accepted proposal and vice
        versa. Ranking them by status first let a recovery re-propose a
        stale ballot-zero Accept over a decided higher-ballot invalidation,
        splitting replicas between STABLE and INVALIDATED (burn seed 6000).
        Decided statuses (PreCommitted+) still dominate every accept."""
        phase = (SaveStatus.ACCEPTED
                 if self.status in (SaveStatus.ACCEPTED,
                                    SaveStatus.ACCEPTED_INVALIDATE)
                 else self.status)
        return (phase, self.accepted_ballot, self.status)

    def merge(self, other: "RecoverOk") -> "RecoverOk":
        """Cross-shard / cross-node knowledge union (BeginRecovery.reduce;
        `hi` per _rank — for the accept phase the highest-ballot proposal
        is the one recovery must adopt)."""
        hi, lo = ((self, other) if self._rank() >= other._rank()
                  else (other, self))
        accepted_ballot = max(self.accepted_ballot, other.accepted_ballot)
        partial_txn = (self.partial_txn.with_(other.partial_txn)
                       if self.partial_txn is not None
                       and other.partial_txn is not None
                       else self.partial_txn or other.partial_txn)
        writes = (hi.writes.merge(lo.writes) if hi.writes is not None
                  else lo.writes)
        witness = self.earlier_committed_witness.with_(
            other.earlier_committed_witness)
        no_witness = self.earlier_no_witness.with_(
            other.earlier_no_witness).without(witness.contains)
        return RecoverOk(
            self.txn_id, hi.status, accepted_ballot, hi.execute_at,
            self.latest_deps.merge(other.latest_deps), partial_txn,
            writes,
            hi.result if hi.result is not None else lo.result,
            self.rejects_fast_path or other.rejects_fast_path,
            witness, no_witness,
            self.unresolved_covers.with_(other.unresolved_covers),
            invalid_if=max(self.invalid_if, other.invalid_if))

    def __repr__(self):
        return (f"RecoverOk({self.txn_id!r}, {self.status.name}, "
                f"rejectsFP={self.rejects_fast_path})")


class RecoverNack(Reply):
    type = MessageType.BEGIN_RECOVER_RSP

    def __init__(self, superseded_by: Ballot):
        self.superseded_by = superseded_by

    def __repr__(self):
        return f"RecoverNack({self.superseded_by!r})"


class BeginRecovery(TxnRequest):
    type = MessageType.BEGIN_RECOVER_REQ

    def __init__(self, txn_id: TxnId, scope: Route, ballot: Ballot,
                 partial_txn: Optional[PartialTxn] = None,
                 full_route: Route = None):
        super().__init__(txn_id, scope, full_route=full_route)
        self.ballot = ballot
        # definition is optional: the recovering coordinator sends its local
        # slice if it has one; replicas that witnessed keep their own
        self.partial_txn = partial_txn

    def apply(self, safe_store) -> Reply:
        from accord_tpu.coordinate.infer import invalid_if_local
        outcome, cmd = C.recover(safe_store, self.txn_id, self.partial_txn,
                                 self.route, self.ballot)
        if outcome == C.AcceptOutcome.REJECTED_BALLOT:
            return RecoverNack(cmd.promised)
        if outcome == C.AcceptOutcome.TRUNCATED:
            # genuinely invalidated, locally shed, or a fence REFUSAL
            # (Commands.recover's durable-fence gate): report what we know,
            # attaching the InvalidIf evidence when undecided so the
            # coordinator can fold a quorum of refusals into a no-round
            # commit-invalidate (coordinate/infer.py)
            evidence = InvalidIf.NOT_KNOWN_TO_BE_INVALID
            if cmd.save_status == SaveStatus.INVALIDATED:
                evidence = InvalidIf.IS_INVALID
            elif not cmd.save_status.is_decided:
                evidence = invalid_if_local(
                    safe_store, self.txn_id,
                    self._local_keys(safe_store, cmd))
            return RecoverOk(self.txn_id, cmd.save_status, cmd.accepted_ballot,
                             cmd.execute_at, LatestDeps.EMPTY, None,
                             None, None, False, Deps.NONE, Deps.NONE,
                             invalid_if=evidence)

        keys = self._local_keys(safe_store, cmd)
        local_deps = None
        rejects = False
        earlier_witness = Deps.NONE
        earlier_no_witness = Deps.NONE
        unresolved_covers = Deps.NONE
        known_deps = cmd.known().deps
        if known_deps < KnownDeps.COMMITTED:
            # no committed/decided deps held here: contribute a fresh local
            # calculation — including for PRE_COMMITTED replicas, whose
            # executeAt arrived by Propagate without deps
            # (BeginRecovery.java:115-119 hasCommittedOrDecidedDeps gate)
            local_deps = C.calculate_deps(safe_store, self.txn_id, keys,
                                          before=self.txn_id)
        if not cmd.has_been(SaveStatus.PRE_COMMITTED):
            # fast-path decipher predicates only matter pre-decision
            rejects, unresolved_covers = safe_store.decipher_fast_path(
                self.txn_id, keys)
            earlier_witness = safe_store.earlier_committed_witness(
                self.txn_id, keys)
            earlier_no_witness = safe_store.earlier_accepted_no_witness(
                self.txn_id, keys)
        # coordinated = whatever a coordinator durably handed us: the Accept
        # proposal (PROPOSED) or the commit's deps (COMMITTED/STABLE)
        coordinated = (cmd.stable_deps if cmd.stable_deps is not None
                       else cmd.partial_deps)
        latest = LatestDeps.create(safe_store.ranges, known_deps,
                                   cmd.accepted_ballot, coordinated,
                                   local_deps)
        evidence = (invalid_if_local(safe_store, self.txn_id, keys)
                    if not cmd.save_status.is_decided
                    else InvalidIf.NOT_KNOWN_TO_BE_INVALID)
        return RecoverOk(
            self.txn_id, cmd.save_status, cmd.accepted_ballot, cmd.execute_at,
            latest, cmd.partial_txn, cmd.writes, cmd.result,
            rejects, earlier_witness, earlier_no_witness, unresolved_covers,
            invalid_if=evidence)

    def _local_keys(self, safe_store, cmd):
        """Participants (Keys or Ranges) for deps calc + decipher predicates."""
        if cmd.partial_txn is not None:
            return cmd.partial_txn.keys
        if self.partial_txn is not None:
            return self.partial_txn.keys
        if not self.scope.is_key_domain:
            return self.scope.ranges
        return self.scope.participant_keys()

    def recovery_probe(self):
        # Keys OR Ranges: the device store materializes a Ranges probe into
        # the CFK keys inside the ranges at snapshot time (the per-key
        # predicate tier a range-domain recovery walks), with serve-time
        # cover/version gates guarding any divergence
        if self.partial_txn is not None:
            return (self.txn_id, self.partial_txn.keys)
        if self.scope.is_key_domain:
            return (self.txn_id, self.scope.participant_keys())
        return (self.txn_id, self.scope.ranges)

    def deps_probe(self):
        # apply() also contributes a fresh local deps calculation when no
        # committed deps are held (calculate_deps at before=txn_id); declare
        # it so the device window precomputes it alongside the recovery
        # predicates.  The serve-time key set (_local_keys, state-dependent)
        # must be covered by this declaration or the scan falls back to the
        # scalar walk — which the cover/version gates enforce.
        keys = (self.partial_txn.keys if self.partial_txn is not None
                else (self.scope.participant_keys()
                      if self.scope.is_key_domain else self.scope.ranges))
        return (self.txn_id, self.txn_id.kind.witnesses(), keys)

    def reduce(self, a: Reply, b: Reply) -> Reply:
        if isinstance(a, RecoverNack):
            return a
        if isinstance(b, RecoverNack):
            return b
        assert isinstance(a, RecoverOk) and isinstance(b, RecoverOk)
        return a.merge(b)

    def __repr__(self):
        return f"BeginRecovery({self.txn_id!r}, b={self.ballot!r})"
