"""MultiPreAccept: one wire envelope carrying a batch's requests.

The ingest pipeline (accord_tpu/pipeline/) coalesces the fan-out of a whole
micro-batch so ONE wire message per replica carries every request the
batch's coordinations sent there — dominated by PreAccepts at batch start,
plus Commits/Stables/Applies when the host loop holds a coalescing window
open across a reply burst.  The receiver unpacks each part back into the
ordinary 48-verb registry path (`Node.receive` per part, preserving each
part's own reply context, epoch gate and journaling), so the local state
machine is untouched by batching.

While the parts are applied, every local command store's flush window is
pinned (CommandStore.hold_flush/release_flush — a no-op on scalar stores):
the batched device tier therefore resolves the whole envelope's deps/
recovery/execution probes as ONE fused kernel window regardless of its
configured flush delay, which is the point of batching at admission.

The envelope itself carries MessageType None: it is transport framing, not
a protocol verb — it has no side effects of its own (each side-effecting
part journals individually), and dropping it equals dropping its parts on
a lossy link (RPC timeouts and the progress log heal, as always).
"""

from __future__ import annotations

from typing import Iterable, Tuple

from accord_tpu.messages.base import Request


class MultiPreAccept(Request):
    """Batch envelope: `parts` is a tuple of (reply_context, request) pairs.

    Reply contexts are opaque transport tokens minted by the SENDER's sink
    when it registered each part's callback (an int msg-id on the framed
    hosts, an (origin, msg_id) pair in the sim); the receiver hands each
    one back through `node.reply` exactly as it would for an individually
    delivered request, so replies travel the ordinary path."""

    def __init__(self, parts: Iterable[Tuple[object, Request]]):
        self.parts = tuple(parts)

    @property
    def wait_for_epoch(self) -> int:
        # parts re-enter Node.receive individually, where each one applies
        # its own epoch gate; gating the envelope on the max would stall
        # every part behind the batch's newest-epoch member
        return 0

    def process(self, node, from_id: int, reply_context) -> None:
        stores = node.command_stores.all()
        for store in stores:
            store.hold_flush()
        try:
            for ctx, part in self.parts:
                node.receive(part, from_id, ctx)
        finally:
            for store in stores:
                store.release_flush()

    def __repr__(self):
        return f"MultiPreAccept(n={len(self.parts)})"
