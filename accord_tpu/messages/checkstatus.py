"""CheckStatus: interrogate peers about a transaction, merging knowledge.

Reference: accord/messages/CheckStatus.java:78 — IncludeInfo levels (No/
Route/All), CheckStatusOk / CheckStatusOkFull replies whose `merge` keeps the
maximum knowledge per field. Used by FindRoute (route discovery), MaybeRecover
(has anyone progressed?), and FetchData (pull definition/deps/outcome).
"""

from __future__ import annotations

import enum
from typing import Optional

from accord_tpu.local.status import (Durability, InvalidIf, Known,
                                     ProgressToken, SaveStatus)
from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Range, Ranges, Route
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnId
from accord_tpu.primitives.txn import PartialTxn
from accord_tpu.primitives.writes import Writes
from accord_tpu.utils.interval_map import ReducingRangeMap


class IncludeInfo(enum.Enum):
    NO = "No"
    ROUTE = "Route"
    ALL = "All"


def _token_spans(participants):
    """[(start, end)) token spans of a Keys/RoutingKeys or Ranges selection."""
    if isinstance(participants, Range):
        return [(participants.start, participants.end)]
    if not isinstance(participants, Ranges):
        participants = participants.to_ranges()
    return [(r.start, r.end) for r in participants]


class KnownMap:
    """Per-range knowledge provenance (reference CheckStatus.FoundKnownMap:
    298): which Known vector is justified over which token spans. Each
    replying replica builds one over the participants its store actually
    covers; merging replies takes the range-wise at_least; consumers ask
    known_for(owned) — Known.reduce across every owned span, with
    Known.NOTHING standing in for any uncovered gap — so a partial-quorum
    merge cannot overclaim per-range knowledge (definition, deps) for shards
    that never replied, while still crediting global facts (executeAt,
    outcome) decided anywhere (FoundKnownMap.knownFor)."""

    __slots__ = ("_map",)

    EMPTY: "KnownMap"

    def __init__(self, _map: Optional[ReducingRangeMap] = None):
        self._map = _map if _map is not None else ReducingRangeMap()

    @classmethod
    def create(cls, participants, known: Known) -> "KnownMap":
        m = ReducingRangeMap()
        for s, e in _token_spans(participants):
            m = m.update(s, e, known, Known.at_least)
        return cls(m)

    def merge(self, other: "KnownMap") -> "KnownMap":
        return KnownMap(self._map.merge(other._map, Known.at_least))

    def known_for(self, participants) -> Known:
        """The Known vector valid across ALL the given participants."""
        def f(acc, v):
            k = v if v is not None else Known.NOTHING
            return k if acc is None else acc.reduce(k)

        acc = None
        for s, e in _token_spans(participants):
            acc = self._map.fold_intersecting(s, e, f, acc)
        return acc if acc is not None else Known.NOTHING

    def known_for_any(self) -> Known:
        """The at_least union over every span (FoundKnownMap.knownForAny)."""
        acc = Known.NOTHING
        for _s, _e, v in self._map.spans():
            if v is not None:
                acc = acc.at_least(v)
        return acc

    def __eq__(self, other):
        return isinstance(other, KnownMap) and self._map == other._map

    def __repr__(self):
        return f"KnownMap({self._map!r})"


KnownMap.EMPTY = KnownMap()


class CheckStatusOk(Reply):
    """Everything one replica knows (CheckStatus.CheckStatusOk; with
    include_info=ALL also the Full fields: definition, deps, outcome)."""

    type = MessageType.CHECK_STATUS_RSP

    def __init__(self, save_status: SaveStatus, promised: Ballot,
                 accepted: Ballot, execute_at: Optional[Timestamp],
                 durability: Durability, route: Optional[Route],
                 is_coordinating: bool = False,
                 partial_txn: Optional[PartialTxn] = None,
                 stable_deps: Optional[Deps] = None,
                 writes: Optional[Writes] = None, result=None,
                 invalid_if_undecided: bool = False,
                 known_map: Optional[KnownMap] = None):
        self.save_status = save_status
        self.promised = promised
        self.accepted = accepted
        self.execute_at = execute_at
        self.durability = durability
        self.route = route
        self.is_coordinating = is_coordinating
        self.partial_txn = partial_txn
        self.stable_deps = stable_deps
        self.writes = writes
        self.result = result
        # durability-derived evidence this txn is headed for invalidation
        # (coordinate/infer.py); under ACCORD_INFER_FULL=0 it steers the
        # fetcher's escalation into the ballot-backed Invalidate round;
        # the full ladder instead reads the per-range InvalidIf lattice
        # carried inside known_map (see invalid_if below)
        self.invalid_if_undecided = invalid_if_undecided
        # per-range knowledge provenance; None only for legacy/hand-built
        # replies, in which case known_for falls back to the global vector
        self.known_map = known_map

    def merge(self, other: "CheckStatusOk") -> "CheckStatusOk":
        """Field-wise maximum knowledge (CheckStatusOk.merge)."""
        hi, lo = (self, other) if self.save_status >= other.save_status \
            else (other, self)
        route = hi.route
        if route is None or (lo.route is not None and lo.route.is_full
                             and not route.is_full):
            route = lo.route if lo.route is not None else route
        elif route is not None and lo.route is not None \
                and not route.is_full and not lo.route.is_full:
            route = route.with_(lo.route)
        return CheckStatusOk(
            hi.save_status,
            Ballot.max(self.promised, other.promised),
            Ballot.max(self.accepted, other.accepted),
            hi.execute_at if hi.execute_at is not None else lo.execute_at,
            max(self.durability, other.durability),
            route,
            self.is_coordinating or other.is_coordinating,
            # UNION the definitions (RecoverOk.merge does the same):
            # replicas hold slices of the txn body; keeping just one side
            # could later reconstitute a partial body as the whole txn and
            # silently drop other shards' reads/updates
            (hi.partial_txn.with_(lo.partial_txn)
             if hi.partial_txn is not None and lo.partial_txn is not None
             else hi.partial_txn if hi.partial_txn is not None
             else lo.partial_txn),
            # UNION the stable deps too (CheckStatusOkFull.merge:820-822
            # `fullMax.stableDeps.with(fullMin.stableDeps)`): each STABLE
            # replica holds the deps slice for ITS ranges only; keeping one
            # side would leave the known_map claiming deps-STABLE over
            # ranges whose actual deps were on the discarded side
            (hi.stable_deps.with_(lo.stable_deps)
             if hi.stable_deps is not None and lo.stable_deps is not None
             else hi.stable_deps if hi.stable_deps is not None
             else lo.stable_deps),
            # reunite writes: commands now store the FULL writes (Apply no
            # longer slices at store time), but replies from older partial
            # applications or hand-built sources may still carry slices —
            # the union is correct either way and costs one keys merge
            (hi.writes.merge(lo.writes) if hi.writes is not None
             else lo.writes),
            hi.result if hi.result is not None else lo.result,
            invalid_if_undecided=(self.invalid_if_undecided
                                  or other.invalid_if_undecided),
            known_map=(None if self.known_map is None
                       and other.known_map is None
                       else (self.known_map or KnownMap.EMPTY).merge(
                           other.known_map or KnownMap.EMPTY)),
        )

    def known_for(self, participants) -> Known:
        """The Known vector justified across ALL the given participants —
        Propagate's gate for per-store application (CheckStatusOk via
        FoundKnownMap.knownFor). Falls back to the global projection for
        hand-built replies with no provenance map."""
        if self.known_map is None:
            return self.save_status.known()
        return self.known_map.known_for(participants)

    @property
    def invalid_if(self) -> InvalidIf:
        """The strongest per-range invalidation condition any span of this
        reply carries (Infer.InvalidIf via the KnownMap lattice join) —
        evidence is global, so the span-wise at_least union is the reply's
        claim.  Legacy replies degrade to the boolean projection."""
        if self.known_map is None:
            return (InvalidIf.IF_UNDECIDED if self.invalid_if_undecided
                    else InvalidIf.NOT_KNOWN_TO_BE_INVALID)
        return self.known_map.known_for_any().invalid_if

    def to_progress_token(self) -> ProgressToken:
        """Progress summary for liveness comparisons
        (CheckStatusOk.toProgressToken)."""
        return ProgressToken.of(self.durability, self.save_status,
                                self.promised, self.accepted)

    def __repr__(self):
        return (f"CheckStatusOk({self.save_status.name}, "
                f"at={self.execute_at!r}, route={self.route!r})")


class CheckStatusNack(Reply):
    type = MessageType.CHECK_STATUS_RSP

    def __repr__(self):
        return "CheckStatusNack"


class CheckStatus(TxnRequest):
    type = MessageType.CHECK_STATUS_REQ

    def __init__(self, txn_id: TxnId, scope: Route,
                 include_info: IncludeInfo = IncludeInfo.ROUTE):
        super().__init__(txn_id, scope)
        self.include_info = include_info

    def apply(self, safe_store) -> Reply:
        from accord_tpu.coordinate.infer import (invalid_if_for_span,
                                                 invalid_if_undecided)
        cmd = safe_store.if_present(self.txn_id)
        undecided = cmd is None or not cmd.save_status.is_decided
        proof = (undecided and invalid_if_undecided(
            safe_store, self.txn_id, self.scope.participants()))
        # provenance: this store's knowledge applies only to the scope slice
        # its ranges actually cover (FoundKnownMap.create over command-store
        # ranges, CheckStatus.java:326)
        owned = self.scope.owned_participants(safe_store.ranges)
        known = (Known.NOTHING if cmd is None else cmd.save_status.known())
        if undecided:
            # attach the per-range InvalidIf lattice (Infer.invalidIfNot):
            # each owned span reports the strongest condition ITS durability
            # watermarks justify, so a partial-quorum merge cannot borrow
            # one shard's fence for another's spans
            m = ReducingRangeMap()
            for s, e in _token_spans(owned):
                k = known.with_invalid_if(
                    invalid_if_for_span(safe_store, self.txn_id, s, e))
                m = m.update(s, e, k, Known.at_least)
            known_map = KnownMap(m)
        else:
            known_map = KnownMap.create(owned, known)
        if cmd is None:
            return CheckStatusOk(SaveStatus.NOT_DEFINED, Ballot.ZERO,
                                 Ballot.ZERO, None, Durability.NOT_DURABLE,
                                 None, invalid_if_undecided=proof,
                                 known_map=known_map)
        full = self.include_info == IncludeInfo.ALL
        return CheckStatusOk(
            cmd.save_status, cmd.promised, cmd.accepted_ballot,
            cmd.execute_at, cmd.durability,
            cmd.route if self.include_info != IncludeInfo.NO else None,
            is_coordinating=self.txn_id in safe_store.node.coordinating,
            partial_txn=cmd.partial_txn if full else None,
            stable_deps=cmd.stable_deps if full else None,
            writes=cmd.writes if full else None,
            result=cmd.result if full else None,
            invalid_if_undecided=proof,
            known_map=known_map)

    def reduce(self, a: Reply, b: Reply) -> Reply:
        if isinstance(a, CheckStatusNack):
            return b
        if isinstance(b, CheckStatusNack):
            return a
        return a.merge(b)

    def __repr__(self):
        return f"CheckStatus({self.txn_id!r}, {self.include_info.value})"
