"""Epoch-sync gossip and the bootstrap data-fetch verb.

Reference: epoch sync is the ConfigurationService/EpochReady contract
(api/ConfigurationService.java — nodes acknowledge an epoch once their data
for it is ready; TopologyManager.onEpochSyncComplete collects a quorum per
shard before coordination may rely on the new epoch). The data fetch is the
DataStore bootstrap protocol (api/DataStore.java:39-113, FETCH_DATA_REQ
carried by impl/AbstractFetchCoordinator in the reference).
"""

from __future__ import annotations

from typing import Dict, Optional

from accord_tpu.messages.base import MessageType, Reply, Request
from accord_tpu.primitives.keys import Ranges
from accord_tpu.primitives.timestamp import TxnId


class EpochSyncComplete(Request):
    """`from` has finished preparing `epoch` (bootstrap fetched, stores
    re-ranged): counts toward the per-shard sync quorum that unlocks
    coordination in the new epoch (TopologyManager.onEpochSyncComplete)."""

    def __init__(self, epoch: int):
        self.epoch = epoch

    def process(self, node, from_id: int, reply_context) -> None:
        node.topology.on_epoch_sync_complete(from_id, self.epoch)

    def __repr__(self):
        return f"EpochSyncComplete({self.epoch})"


class FetchSnapshotOk(Reply):
    type = MessageType.FETCH_DATA_RSP

    def __init__(self, snapshot, ranges: Ranges, max_applied=None):
        self.snapshot = snapshot  # opaque DataStore payload
        self.ranges = ranges      # what the peer actually covered
        # the source's max applied executeAt within `ranges` — the optional
        # bound of DataStore.StartingRangeFetch.started(maxApplied), letting
        # the fetcher raise its clocks without a separate global probe
        self.max_applied = max_applied

    def __repr__(self):
        return f"FetchSnapshotOk({self.ranges!r})"


class FetchSnapshotNack(Reply):
    type = MessageType.FETCH_DATA_RSP

    def __repr__(self):
        return "FetchSnapshotNack"


class FetchSnapshot(Request):
    """Bootstrap fetch: once `fence` (the bootstrap ExclusiveSyncPoint) has
    applied at the peer, its data for `ranges` contains every transaction
    ordered below the fence — snapshot and return it."""

    type = MessageType.FETCH_DATA_REQ

    def __init__(self, txn_id: TxnId, ranges: Ranges):
        self.txn_id = txn_id  # the fence ESP
        self.ranges = ranges

    @property
    def wait_for_epoch(self) -> int:
        return self.txn_id.epoch

    def process(self, node, from_id: int, reply_context) -> None:
        from accord_tpu.local.command import OnAppliedListener
        from accord_tpu.local.store import PreLoadContext

        stores = node.command_stores.intersecting(self.ranges)
        if not stores:
            node.reply(from_id, reply_context, FetchSnapshotNack())
            return
        covered = Ranges.EMPTY
        for s in stores:
            covered = covered.union(s.ranges.slice(self.ranges))
        if covered.is_empty:
            node.reply(from_id, reply_context, FetchSnapshotNack())
            return
        remaining = {s.id for s in stores}

        def on_all_applied():
            snap = node.data_store.snapshot_ranges(covered)
            max_applied = None
            for s in stores:
                for key, tfk in s.tfks.items():
                    if tfk.last_executed is not None and covered.contains(key) \
                            and (max_applied is None
                                 or tfk.last_executed > max_applied):
                        max_applied = tfk.last_executed
            node.reply(from_id, reply_context,
                       FetchSnapshotOk(snap, covered, max_applied))

        def arm(safe_store):
            from accord_tpu.local.status import SaveStatus
            sid = safe_store.store.id

            def fired(_cmd):
                remaining.discard(sid)
                if not remaining:
                    on_all_applied()

            cmd = safe_store.get(self.txn_id)
            listener = OnAppliedListener.arm(cmd, fired)
            if not listener.fired and not cmd.has_been(SaveStatus.STABLE):
                # chase the fence if it hasn't reached us yet
                safe_store.progress_log.waiting(
                    self.txn_id, safe_store.store, "Applied", cmd.route,
                    self.ranges)

        for s in stores:
            s.execute(PreLoadContext.for_txn(self.txn_id), arm)

    def __repr__(self):
        return f"FetchSnapshot({self.ranges!r} fenced by {self.txn_id!r})"
