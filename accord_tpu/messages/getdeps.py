"""GetDeps: standalone dependency collection.

Reference: accord/messages/GetDeps.java — calculates deps for `keys` bounded
by `before` (an executeAt), as the Accept round does; used by recovery
(CollectDeps) to fill deps for shards whose committed deps were unreachable,
and by sync points.
"""

from __future__ import annotations

from accord_tpu.local import commands as C
from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Key, Keys, Route
from accord_tpu.primitives.timestamp import Timestamp, TxnId


class GetDepsOk(Reply):
    type = MessageType.GET_DEPS_RSP

    def __init__(self, deps: Deps):
        self.deps = deps

    def __repr__(self):
        return f"GetDepsOk({self.deps!r})"


class GetDeps(TxnRequest):
    type = MessageType.GET_DEPS_REQ

    def __init__(self, txn_id: TxnId, scope: Route, keys: Keys,
                 before: Timestamp):
        super().__init__(txn_id, scope)
        self.keys = keys
        self.before = before

    def deps_probe(self):
        return (self.before, self.txn_id.kind.witnesses(), self.keys)

    def apply(self, safe_store) -> Reply:
        deps = C.calculate_deps(safe_store, self.txn_id, self.keys,
                                before=self.before)
        return GetDepsOk(deps)

    def reduce(self, a: Reply, b: Reply) -> Reply:
        return GetDepsOk(a.deps.with_(b.deps))

    def __repr__(self):
        return f"GetDeps({self.txn_id!r} before {self.before!r})"
