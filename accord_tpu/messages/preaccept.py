"""PreAccept: witness a txn and vote on its executeAt (the fast-path round).

Reference: accord/messages/PreAccept.java:37 — per-shard Commands.preaccept +
calculatePartialDeps (:107-138, 245-266); cross-shard reduce merges max
witnessedAt + union deps (:141-156).
"""

from __future__ import annotations

from typing import Optional

from accord_tpu.local import commands as C
from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Keys, Route
from accord_tpu.primitives.timestamp import Timestamp, TxnId
from accord_tpu.primitives.txn import PartialTxn


class PreAcceptOk(Reply):
    type = MessageType.PRE_ACCEPT_RSP

    def __init__(self, txn_id: TxnId, witnessed_at: Timestamp, deps: Deps):
        self.txn_id = txn_id
        self.witnessed_at = witnessed_at
        self.deps = deps

    @property
    def is_fast_path_vote(self) -> bool:
        return self.witnessed_at == self.txn_id

    def __repr__(self):
        return f"PreAcceptOk({self.txn_id!r}@{self.witnessed_at!r})"


class PreAcceptNack(Reply):
    type = MessageType.PRE_ACCEPT_RSP

    def __repr__(self):
        return "PreAcceptNack"


class PreAccept(TxnRequest):
    type = MessageType.PRE_ACCEPT_REQ

    def __init__(self, txn_id: TxnId, partial_txn: PartialTxn, scope: Route,
                 max_epoch: int, full_route: Route = None):
        super().__init__(txn_id, scope, wait_for_epoch=max_epoch,
                         full_route=full_route)
        self.partial_txn = partial_txn
        self.max_epoch = max_epoch

    def apply(self, safe_store) -> Reply:
        outcome, witnessed_at = C.preaccept(
            safe_store, self.txn_id, self.partial_txn, self.route)
        if outcome in (C.AcceptOutcome.SUCCESS, C.AcceptOutcome.REDUNDANT):
            deps = C.calculate_deps(
                safe_store, self.txn_id, self.partial_txn.keys,
                before=self.txn_id)
            return PreAcceptOk(self.txn_id, witnessed_at, deps)
        return PreAcceptNack()

    def deps_probe(self):
        # Keys OR Ranges: the key tier serves Keys probes from the batched
        # CFK kernel; the range-stab tier (ops/range_kernel.py) serves the
        # range-command arm for both domains
        return (self.txn_id, self.txn_id.kind.witnesses(),
                self.partial_txn.keys)

    def reduce(self, a: Reply, b: Reply) -> Reply:
        if isinstance(a, PreAcceptNack):
            return a
        if isinstance(b, PreAcceptNack):
            return b
        assert isinstance(a, PreAcceptOk) and isinstance(b, PreAcceptOk)
        return PreAcceptOk(self.txn_id,
                           Timestamp.max(a.witnessed_at, b.witnessed_at),
                           a.deps.with_(b.deps))

    def __repr__(self):
        return f"PreAccept({self.txn_id!r})"
