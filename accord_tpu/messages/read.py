"""The execution-epoch read path.

Reference: accord/messages/ReadData.java:52-370 — registers as a transient
listener on the command until ReadyToExecute/Applied, then executes txn.read
against the DataStore and replies ReadOk{data, unavailable}; obsolescence
handling via commit/invalidate transitions.
"""

from __future__ import annotations

from typing import Optional

from accord_tpu.api.data import Data
from accord_tpu.local.command import Command, TransientListener
from accord_tpu.local.status import SaveStatus
from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.primitives.keys import Keys, Ranges, Route
from accord_tpu.primitives.timestamp import Timestamp, TxnId
from accord_tpu.utils.async_chains import AsyncResult


class ReadOk(Reply):
    type = MessageType.READ_RSP

    def __init__(self, data: Optional[Data], unavailable: Optional[Ranges] = None):
        self.data = data
        self.unavailable = unavailable

    def merge(self, other: "ReadOk") -> "ReadOk":
        data = (self.data.merge(other.data)
                if self.data is not None and other.data is not None
                else self.data or other.data)
        unavailable = self.unavailable or other.unavailable
        return ReadOk(data, unavailable)

    def __repr__(self):
        return f"ReadOk({self.data!r})"


class ReadNack(Reply):
    type = MessageType.READ_RSP

    INVALID = "Invalid"       # command invalidated
    REDUNDANT = "Redundant"   # already applied/truncated elsewhere
    NOT_COMMITTED = "NotCommitted"
    UNAVAILABLE = "Unavailable"  # data not yet bootstrapped locally

    def __init__(self, reason: str):
        self.reason = reason

    def __repr__(self):
        return f"ReadNack({self.reason})"


class _ReadWhenReady(TransientListener):
    """Wait for ReadyToExecute (deps applied), then read at executeAt."""

    def __init__(self, safe_store, txn_id: TxnId, keys: Keys,
                 result: AsyncResult):
        self.txn_id = txn_id
        self.keys = keys
        self.result = result
        self.done = False

    def on_change(self, safe_store, command: Command) -> None:
        self.maybe_read(safe_store, command)

    def maybe_read(self, safe_store, command: Command) -> None:
        if self.done:
            return
        status = command.save_status
        if status == SaveStatus.INVALIDATED:
            self._finish(command, ReadNack(ReadNack.INVALID))
        elif status.is_truncated or status >= SaveStatus.PRE_APPLIED:
            # obsolete: the outcome is already known (possibly applied) — the
            # pre-write snapshot no longer exists here (ReadData.java
            # obsolescence; reading post-apply state would violate
            # serializability)
            self._finish(command, ReadNack(ReadNack.REDUNDANT))
        elif status == SaveStatus.READY_TO_EXECUTE:
            self._do_read(safe_store, command)

    def _do_read(self, safe_store, command: Command) -> None:
        txn = command.partial_txn
        owned = self.keys.slice(safe_store.ranges) \
            if not safe_store.ranges.is_empty else self.keys
        if txn is None or txn.read is None or not owned:
            self._finish(command, ReadOk(None))
            return
        if not safe_store.is_safe_to_read(owned):
            self._finish(command, ReadNack(ReadNack.UNAVAILABLE))
            return
        from accord_tpu.local.watermarks import PreBootstrapOrStale
        if safe_store.store.redundant_before.pre_bootstrap_or_stale(
                self.txn_id, owned) != PreBootstrapOrStale.POST_BOOTSTRAP:
            # our bootstrap snapshot may already embed this txn's own writes
            # (and its successors'): the pre-execution snapshot no longer
            # exists here — another replica must serve it
            self._finish(command, ReadNack(ReadNack.UNAVAILABLE))
            return
        self.done = True
        command.remove_transient_listener(self)
        txn.read_data(command.execute_at, safe_store.data_store,
                      on_keys=owned).add_callback(
            lambda data, failure: self.result.try_failure(failure)
            if failure is not None else self.result.try_success(ReadOk(data)))

    def _finish(self, command: Command, reply: Reply) -> None:
        self.done = True
        command.remove_transient_listener(self)
        self.result.try_success(reply)


def execute_read_when_ready(safe_store, txn_id: TxnId, keys: Keys
                            ) -> AsyncResult:
    """Arrange for the local read of `keys` once txn is ready; returns
    AsyncResult[ReadOk|ReadNack]."""
    result: AsyncResult = AsyncResult()
    command = safe_store.get(txn_id)
    listener = _ReadWhenReady(safe_store, txn_id, keys, result)
    command.add_transient_listener(listener)
    listener.maybe_read(safe_store, command)
    return result


class ReadTxnData(TxnRequest):
    """Standalone read request (READ_REQ): used when the read set differs from
    the stable set or on retry (ReadData.java / ReadTxnData)."""

    type = MessageType.READ_REQ

    def __init__(self, txn_id: TxnId, scope: Route, read_keys: Keys,
                 execute_at_epoch: int):
        super().__init__(txn_id, scope, wait_for_epoch=execute_at_epoch)
        self.read_keys = read_keys

    def apply(self, safe_store):
        command = safe_store.get(self.txn_id)
        if not command.has_been(SaveStatus.STABLE):
            return ReadNack(ReadNack.NOT_COMMITTED)
        return execute_read_when_ready(safe_store, self.txn_id, self.read_keys)

    def reduce(self, a, b):
        if isinstance(a, ReadNack):
            return a
        if isinstance(b, ReadNack):
            return b
        return a.merge(b)
