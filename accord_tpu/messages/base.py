"""Message plumbing: the verb registry, request/reply bases, and callbacks.

Reference: accord/messages/MessageType.java:34-82 (48 verbs: 44 remote + 4
local-only PROPAGATE), TxnRequest.java:42 (scope computation :259-270,
waitForEpoch :235-252; `process()` IS the map-reduce over command stores),
Callback.java / SafeCallback.java (executor-affine reply callbacks).
"""

from __future__ import annotations

import enum
from typing import Callable, Dict, Optional, TYPE_CHECKING

from accord_tpu.primitives.keys import Ranges, Route
from accord_tpu.primitives.timestamp import TxnId
from accord_tpu.utils import invariants

if TYPE_CHECKING:
    from accord_tpu.local.node import Node


class MessageType(enum.Enum):
    """The complete verb set (MessageType.java:34-82). `has_side_effects`
    drives journaling: verbs that mutate durable command state must be
    replayable."""

    PRE_ACCEPT_REQ = ("PRE_ACCEPT_REQ", True)
    PRE_ACCEPT_RSP = ("PRE_ACCEPT_RSP", False)
    ACCEPT_REQ = ("ACCEPT_REQ", True)
    ACCEPT_RSP = ("ACCEPT_RSP", False)
    ACCEPT_INVALIDATE_REQ = ("ACCEPT_INVALIDATE_REQ", True)
    GET_DEPS_REQ = ("GET_DEPS_REQ", False)
    GET_DEPS_RSP = ("GET_DEPS_RSP", False)
    GET_EPHEMERAL_READ_DEPS_REQ = ("GET_EPHEMERAL_READ_DEPS_REQ", False)
    GET_EPHEMERAL_READ_DEPS_RSP = ("GET_EPHEMERAL_READ_DEPS_RSP", False)
    GET_MAX_CONFLICT_REQ = ("GET_MAX_CONFLICT_REQ", False)
    GET_MAX_CONFLICT_RSP = ("GET_MAX_CONFLICT_RSP", False)
    COMMIT_SLOW_PATH_REQ = ("COMMIT_SLOW_PATH_REQ", True)
    COMMIT_MAXIMAL_REQ = ("COMMIT_MAXIMAL_REQ", True)
    STABLE_FAST_PATH_REQ = ("STABLE_FAST_PATH_REQ", True)
    STABLE_SLOW_PATH_REQ = ("STABLE_SLOW_PATH_REQ", True)
    STABLE_MAXIMAL_REQ = ("STABLE_MAXIMAL_REQ", True)
    COMMIT_INVALIDATE_REQ = ("COMMIT_INVALIDATE_REQ", True)
    APPLY_MINIMAL_REQ = ("APPLY_MINIMAL_REQ", True)
    APPLY_MAXIMAL_REQ = ("APPLY_MAXIMAL_REQ", True)
    APPLY_RSP = ("APPLY_RSP", False)
    READ_REQ = ("READ_REQ", False)
    READ_EPHEMERAL_REQ = ("READ_EPHEMERAL_REQ", False)
    READ_RSP = ("READ_RSP", False)
    BEGIN_RECOVER_REQ = ("BEGIN_RECOVER_REQ", True)
    BEGIN_RECOVER_RSP = ("BEGIN_RECOVER_RSP", False)
    BEGIN_INVALIDATE_REQ = ("BEGIN_INVALIDATE_REQ", True)
    BEGIN_INVALIDATE_RSP = ("BEGIN_INVALIDATE_RSP", False)
    WAIT_ON_COMMIT_REQ = ("WAIT_ON_COMMIT_REQ", False)
    WAIT_ON_COMMIT_RSP = ("WAIT_ON_COMMIT_RSP", False)
    WAIT_UNTIL_APPLIED_REQ = ("WAIT_UNTIL_APPLIED_REQ", False)
    INFORM_OF_TXN_REQ = ("INFORM_OF_TXN_REQ", True)
    INFORM_DURABLE_REQ = ("INFORM_DURABLE_REQ", True)
    INFORM_HOME_DURABLE_REQ = ("INFORM_HOME_DURABLE_REQ", True)
    CHECK_STATUS_REQ = ("CHECK_STATUS_REQ", False)
    CHECK_STATUS_RSP = ("CHECK_STATUS_RSP", False)
    FETCH_DATA_REQ = ("FETCH_DATA_REQ", False)
    FETCH_DATA_RSP = ("FETCH_DATA_RSP", False)
    SET_SHARD_DURABLE_REQ = ("SET_SHARD_DURABLE_REQ", True)
    SET_GLOBALLY_DURABLE_REQ = ("SET_GLOBALLY_DURABLE_REQ", True)
    QUERY_DURABLE_BEFORE_REQ = ("QUERY_DURABLE_BEFORE_REQ", False)
    QUERY_DURABLE_BEFORE_RSP = ("QUERY_DURABLE_BEFORE_RSP", False)
    APPLY_THEN_WAIT_UNTIL_APPLIED_REQ = ("APPLY_THEN_WAIT_UNTIL_APPLIED_REQ", True)
    # replica-state auditor (messages/audit.py): read-only cross-replica
    # range digests + drill-down entry fetches — never journaled
    AUDIT_DIGEST_REQ = ("AUDIT_DIGEST_REQ", False)
    AUDIT_DIGEST_RSP = ("AUDIT_DIGEST_RSP", False)
    AUDIT_ENTRIES_REQ = ("AUDIT_ENTRIES_REQ", False)
    AUDIT_ENTRIES_RSP = ("AUDIT_ENTRIES_RSP", False)
    # live-elasticity admin plane (messages/admin.py): epoch installs gossip
    # node-to-node and must be journaled before the admin ack; drain and
    # bootstrap-progress records are WAL lifecycle markers that crash-restart
    # replays to resume (not restart) an interrupted reshard
    EPOCH_INSTALL_MSG = ("EPOCH_INSTALL_MSG", True)
    TOPOLOGY_FETCH_REQ = ("TOPOLOGY_FETCH_REQ", False)
    TOPOLOGY_FETCH_RSP = ("TOPOLOGY_FETCH_RSP", False)
    DRAIN_BEGIN_MSG = ("DRAIN_BEGIN_MSG", True)
    DRAIN_DONE_MSG = ("DRAIN_DONE_MSG", True)
    BOOTSTRAP_CHECKPOINT_MSG = ("BOOTSTRAP_CHECKPOINT_MSG", True)
    BOOTSTRAP_DONE_MSG = ("BOOTSTRAP_DONE_MSG", True)
    # bounded-memory paging tier (messages/paging.py): spill frames and
    # fault-index checkpoints live in the pager's per-incarnation spill
    # store, NEVER the node WAL — has_side_effects=False keeps the live
    # journal path from ever framing one
    SPILL_FRAME_MSG = ("SPILL_FRAME_MSG", False)
    FAULT_INDEX_CHECKPOINT_MSG = ("FAULT_INDEX_CHECKPOINT_MSG", False)
    SIMPLE_RSP = ("SIMPLE_RSP", False)
    FAILURE_RSP = ("FAILURE_RSP", False)
    # local-only (never cross the network; applied via Node.local_request)
    PROPAGATE_PRE_ACCEPT_MSG = ("PROPAGATE_PRE_ACCEPT_MSG", True)
    PROPAGATE_STABLE_MSG = ("PROPAGATE_STABLE_MSG", True)
    PROPAGATE_APPLY_MSG = ("PROPAGATE_APPLY_MSG", True)
    PROPAGATE_OTHER_MSG = ("PROPAGATE_OTHER_MSG", True)

    def __init__(self, label: str, has_side_effects: bool):
        self.label = label
        self.has_side_effects = has_side_effects


class Message:
    type: MessageType = None  # set by subclasses
    # optional per-transaction trace id (obs/spans.py), stamped by
    # Node.send on requests that carry a txn_id.  Set as an INSTANCE
    # attribute so host/wire.py's structural codec round-trips it inside
    # the existing wire envelope; the class default keeps untraced
    # messages allocation-free.
    trace_id: Optional[str] = None


class Reply(Message):
    pass


class Request(Message):
    """A message processed by the receiving node."""

    def process(self, node: "Node", from_id: int, reply_context) -> None:
        raise NotImplementedError

    @property
    def wait_for_epoch(self) -> int:
        """Epoch the receiver must know before processing (TxnRequest
        .waitForEpoch); 0 = no gate."""
        return 0


class TxnRequest(Request):
    """Routed request: carries the per-destination scope slice of the route.
    The request object itself is the map-reduce over intersecting command
    stores (TxnRequest implements MapReduceConsume)."""

    def __init__(self, txn_id: TxnId, scope: Route, wait_for_epoch: int = 0,
                 min_epoch: int = 0, full_route: Optional[Route] = None):
        self.txn_id = txn_id
        self.scope = scope
        # the un-sliced route travels alongside the per-destination scope so
        # every witness can recover the txn (reference PreAccept.java:51,
        # Commit.java:78 carry FullRoute)
        self.full_route = full_route
        self._wait_for_epoch = wait_for_epoch
        self.min_epoch = min_epoch or (wait_for_epoch or txn_id.epoch)

    @property
    def route(self) -> Route:
        """Best route knowledge to record on the command."""
        return self.full_route if self.full_route is not None else self.scope

    @property
    def wait_for_epoch(self) -> int:
        return self._wait_for_epoch or self.txn_id.epoch

    # (id(route), id(owned)) -> (route, owned, scope): a coordination's 3-4
    # rounds re-slice the SAME route object by the SAME memoized per-node
    # Ranges per destination.  Values hold strong refs to both key objects
    # (a live entry's ids cannot be recycled); bounded by wholesale clear.
    _SCOPE_MEMO: Dict[tuple, tuple] = {}

    @staticmethod
    def compute_scope(to_node: int, topologies, route: Route) -> Optional[Route]:
        """Slice of `route` owned by `to_node` across the epoch window
        (TxnRequest.computeScope :259-270)."""
        owned = None
        for topology in topologies:
            r = topology.ranges_for_node(to_node)
            # single-epoch window (the common case): reuse the topology's
            # memoized Ranges without a union copy + renormalize
            owned = r if owned is None else owned.union(r)
        if owned is None:
            owned = Ranges.EMPTY
        memo = TxnRequest._SCOPE_MEMO
        key = (id(route), id(owned))
        hit = memo.get(key)
        if hit is not None and hit[0] is route and hit[1] is owned:
            return hit[2]
        scope = route.slice(owned) if route.intersects(owned) else None
        if len(memo) > 1024:
            memo.clear()
        memo[key] = (route, owned, scope)
        return scope

    def process(self, node: "Node", from_id: int, reply_context) -> None:
        node.map_reduce_consume_local(self, from_id, reply_context)

    # subclasses implement the map/reduce:
    def apply(self, safe_store):
        raise NotImplementedError

    def reduce(self, a, b):
        raise NotImplementedError

    def participants(self):
        return self.scope.participants()

    def deps_probe(self):
        """(before, witness KindSet, data Keys) of the active-conflict scan
        apply() will run, or None. Lets a batched device store precompute the
        window's deps in one kernel call (PreLoadContext.deps_probes)."""
        return None

    def recovery_probe(self):
        """(txn_id, data Keys) of the recovery predicate scans apply() will
        run (the four mapReduceFull queries of BeginRecovery), or None —
        the batched device store precomputes them per flush window
        (PreLoadContext.recovery_probes, ops/recovery_kernel.py)."""
        return None

    def execute_probe(self):
        """(txn_id, execute_at, data Keys) of the execution this message
        delivers (Apply), or None — the batched device store plans the
        flush window's apply order with the wavefront kernel
        (PreLoadContext.execute_probes, ops/wavefront.py)."""
        return None


class SimpleReply(Reply):
    type = MessageType.SIMPLE_RSP

    OK = "Ok"
    NACK = "Nack"

    def __init__(self, outcome: str):
        self.outcome = outcome

    def __eq__(self, other):
        return isinstance(other, SimpleReply) and self.outcome == other.outcome

    def __repr__(self):
        return f"SimpleReply({self.outcome})"


class FailureReply(Reply):
    type = MessageType.FAILURE_RSP

    def __init__(self, failure: BaseException):
        self.failure = failure

    def __repr__(self):
        return f"FailureReply({self.failure!r})"


class Callback:
    """Reply callback for a request sent with Node.send (Callback.java).
    Delivery is pinned to the sending executor in the reference; our stores are
    logically single-threaded so delivery order is the simulator's concern."""

    def on_success(self, from_id: int, reply: Reply) -> None:
        raise NotImplementedError

    def on_failure(self, from_id: int, failure: BaseException) -> None:
        raise NotImplementedError

    def on_callback_failure(self, from_id: int, failure: BaseException) -> None:
        raise failure


class RoundCallback(Callback):
    """Tags replies/failures with the round they belong to, so multi-round
    coordinators (deps->read, stable->apply) can discard stragglers from a
    superseded round instead of mis-crediting them to the current tracker
    (the reference pins callbacks per-message for the same reason,
    SafeCallback.java)."""

    def __init__(self, owner, round_id):
        self.owner = owner
        self.round_id = round_id

    def on_success(self, from_id: int, reply: Reply) -> None:
        self.owner.on_round_success(self.round_id, from_id, reply)

    def on_failure(self, from_id: int, failure: BaseException) -> None:
        self.owner.on_round_failure(self.round_id, from_id, failure)


class FunctionCallback(Callback):
    def __init__(self, on_success: Callable[[int, Reply], None],
                 on_failure: Callable[[int, BaseException], None] = None):
        self._on_success = on_success
        self._on_failure = on_failure

    def on_success(self, from_id: int, reply: Reply) -> None:
        self._on_success(from_id, reply)

    def on_failure(self, from_id: int, failure: BaseException) -> None:
        if self._on_failure is not None:
            self._on_failure(from_id, failure)
