"""Ephemeral reads: the single-round, never-witnessed read path.

Reference: accord/messages/GetEphemeralReadDeps.java (collect the write deps
an invisible read must wait for) and ReadData.java's ReadEphemeralTxnData
variant (wait for the supplied deps to apply locally, then read). The txn is
never recorded as a Command anywhere — EphemeralRead witnesses writes but is
witnessed by nothing (Txn.Kind matrix, Txn.java:220-260) — so there is no
recovery; the coordinator simply retries elsewhere on timeout.
"""

from __future__ import annotations

from typing import List, Optional, Set

from accord_tpu.local import commands as C
from accord_tpu.local.command import TransientListener
from accord_tpu.local.status import SaveStatus
from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.messages.read import ReadNack, ReadOk
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Keys, Route
from accord_tpu.primitives.timestamp import Timestamp, TxnId
from accord_tpu.primitives.txn import PartialTxn
from accord_tpu.utils.async_chains import AsyncResult


class GetEphemeralReadDepsOk(Reply):
    type = MessageType.GET_EPHEMERAL_READ_DEPS_RSP

    def __init__(self, deps: Deps, latest_epoch: int):
        self.deps = deps
        self.latest_epoch = latest_epoch

    def __repr__(self):
        return f"GetEphemeralReadDepsOk({self.deps!r}, epoch={self.latest_epoch})"


class GetEphemeralReadDeps(TxnRequest):
    """Collect every active write the read must order itself after
    (GetEphemeralReadDeps.java: unbounded `before` — the read has no
    executeAt of its own)."""

    type = MessageType.GET_EPHEMERAL_READ_DEPS_REQ

    def __init__(self, txn_id: TxnId, scope: Route, keys: Keys):
        super().__init__(txn_id, scope)
        self.keys = keys

    def deps_probe(self):
        return (Timestamp.max_value(), self.txn_id.kind.witnesses(),
                self.keys)

    def apply(self, safe_store) -> Reply:
        deps = C.calculate_deps(safe_store, self.txn_id, self.keys,
                                before=Timestamp.max_value())
        return GetEphemeralReadDepsOk(deps, safe_store.node.epoch)

    def reduce(self, a: Reply, b: Reply) -> Reply:
        return GetEphemeralReadDepsOk(a.deps.with_(b.deps),
                                      max(a.latest_epoch, b.latest_epoch))

    def __repr__(self):
        return f"GetEphemeralReadDeps({self.txn_id!r})"


class _DepsAppliedWaiter(TransientListener):
    """Fires `on_ready` once every dep command is applied / invalidated /
    truncated locally (the ephemeral analogue of WaitingOn, without a
    Command record to hang it on)."""

    def __init__(self, safe_store, dep_ids: List[TxnId], on_ready,
                 deps: "Deps" = None):
        # on_ready(safe_store) receives the safe store of the task it FIRES
        # in — a deferred fire happens in a later store task, and using the
        # arming task's (released) safe store is a leak the Debug store
        # variant rejects
        self.on_ready = on_ready
        self.pending: Set[TxnId] = set()
        self.fired = False
        # deps this wait created empty NOT_DEFINED records for — removed
        # again once the wait resolves, so the store is not polluted by
        # commands that exist purely to hang a listener on
        self.created: Set[TxnId] = set()
        for dep_id in dep_ids:
            existing = safe_store.if_present(dep_id)
            cmd = existing if existing is not None else safe_store.get(dep_id)
            if not self._cleared(safe_store, cmd):
                if existing is None:
                    self.created.add(dep_id)
                self.pending.add(dep_id)
                cmd.add_transient_listener(self)
                # a dep this replica hasn't committed/applied may never
                # arrive on its own (the Apply could be lost): register a
                # progress-log chase so the missing state is fetched rather
                # than the read hanging until the coordinator times out
                # (the reference ReadData registers the same waiting intent)
                if not cmd.has_been(SaveStatus.PRE_APPLIED):
                    participants = None
                    if deps is not None:
                        key_parts, range_parts = deps.participants(dep_id)
                        participants = key_parts if len(key_parts) > 0 \
                            else range_parts
                    safe_store.progress_log.waiting(
                        dep_id, safe_store.store, "Applied", cmd.route,
                        participants)
        if not self.pending:
            self.fired = True
            on_ready(safe_store)

    @staticmethod
    def _cleared(safe_store, cmd) -> bool:
        if cmd.is_applied_or_gone or cmd.is_truncated:
            return True
        rb = safe_store.store.redundant_before
        if cmd.route is not None and cmd.route.is_key_domain:
            parts = cmd.route.participants()
            if len(parts) > 0 and all(rb.is_redundant(cmd.txn_id, k)
                                      for k in parts):
                return True
        return False

    def on_change(self, safe_store, command) -> None:
        if self.fired or command.txn_id not in self.pending:
            return
        if self._cleared(safe_store, command):
            self.pending.discard(command.txn_id)
            command.remove_transient_listener(self)
            self._maybe_drop_created(safe_store, command)
            if not self.pending:
                self.fired = True
                self.on_ready(safe_store)

    def _maybe_drop_created(self, safe_store, command) -> None:
        """Remove a record that exists purely because this wait created it:
        still NOT_DEFINED (it cleared via truncation/redundancy watermarks,
        not by progressing) and nothing else is listening."""
        if command.txn_id in self.created \
                and command.save_status == SaveStatus.NOT_DEFINED \
                and not command.transient_listeners \
                and not command.listeners:
            safe_store.store.commands.pop(command.txn_id, None)
            # the chase existed for this wait; the store forgot the record,
            # so stop fetching it too
            safe_store.progress_log.clear(command.txn_id)


def wait_for_deps_applied(safe_store, deps: Deps, on_ready) -> None:
    """Arrange `on_ready(live_safe_store)` once every locally-owned dep in
    `deps` has applied — the callback receives the safe store of the task it
    fires in (deferred fires happen in later store tasks)."""
    local = deps.slice(safe_store.ranges) if not safe_store.ranges.is_empty \
        else deps
    _DepsAppliedWaiter(safe_store, local.sorted_txn_ids(), on_ready,
                       deps=local)


class ReadEphemeralTxnData(TxnRequest):
    """Execute the read once `deps` have applied locally
    (READ_EPHEMERAL_REQ; ReadData.java ReadEphemeralTxnData)."""

    type = MessageType.READ_EPHEMERAL_REQ

    def __init__(self, txn_id: TxnId, scope: Route, read_keys: Keys,
                 partial_txn: PartialTxn, deps: Deps, execute_at_epoch: int):
        super().__init__(txn_id, scope, wait_for_epoch=execute_at_epoch)
        self.read_keys = read_keys
        self.partial_txn = partial_txn
        self.deps = deps

    def apply(self, safe_store):
        result: AsyncResult = AsyncResult()
        txn = self.partial_txn
        owned = self.read_keys.slice(safe_store.ranges) \
            if not safe_store.ranges.is_empty else self.read_keys
        if txn.read is None or not owned:
            return ReadOk(None)
        if not safe_store.is_safe_to_read(owned):
            return ReadNack(ReadNack.UNAVAILABLE)

        def do_read(live_safe_store):
            # read "now": the snapshot after every collected write dep — the
            # read mints no timestamp of its own (it is invisible).  Uses
            # the FIRING task's safe store: the arming one is released.
            txn.read_data(live_safe_store.time_now(),
                          live_safe_store.data_store,
                          on_keys=owned).add_callback(
                lambda data, failure: result.try_failure(failure)
                if failure is not None else result.try_success(ReadOk(data)))

        wait_for_deps_applied(safe_store, self.deps, do_read)
        return result

    def reduce(self, a, b):
        if isinstance(a, ReadNack):
            return a
        if isinstance(b, ReadNack):
            return b
        return a.merge(b)

    def __repr__(self):
        return f"ReadEphemeralTxnData({self.txn_id!r})"
