"""Admin-plane verbs for live elasticity: epoch install, drain, bootstrap WAL
markers.

Reference: accord's configuration service contract (accord/topology/
TopologyManager.java + the accord-maelstrom admin channel): topology changes
enter through an out-of-band admin plane, are made durable before they are
acknowledged, and propagate node-to-node so a single admin contact suffices.

Three verb families live here:

  * EpochInstall / TopologyFetchReq|Ok|Nack — the gossiped epoch proposal and
    its gap-fetch. An install is journaled (has_side_effects) BEFORE the
    admin ack, and `impl/config_service.py` applies it through the same
    immutable-topology swap the sim uses.
  * DrainBegin / DrainDone — scale-in lifecycle. The retiring node fences new
    client coordination on DrainBegin; peers deprioritize it as a bootstrap
    source; DrainDone records the durability watermark handoff completed.
  * BootstrapCheckpoint / BootstrapDone — WAL-only progress records written
    by `local/bootstrap.py` as fetched sub-ranges finalize. They are never
    sent to peers: their `process()` is the crash-restart RESTORE path, so a
    node killed mid-bootstrap resumes from the checkpointed coverage instead
    of re-fetching completed ranges.

All admin records replay in a band BEFORE protocol messages
(`replay_band = -1`, journal/snapshot.py): replayed transactions may be
gated on epochs these records install.  None of them carry a `txn_id`
attribute — the compaction fold must keep them in the always-preserved
`no_txn` band, and the reconstruction validator must skip them.
"""

from __future__ import annotations

from typing import Optional, Tuple

from accord_tpu.messages.base import MessageType, Reply, Request
from accord_tpu.primitives.keys import Range, Ranges


class EpochInstall(Request):
    """Propose/forward one topology epoch.

    `shards` is the portable spec `((start, end, (node, ...)), ...)`;
    `peers` optionally carries transport addresses `((id, host, port), ...)`
    — or `((id, host, port, dc), ...)` when a geo profile places the peer
    in a named datacenter — so existing members learn how to reach (and
    where to place) nodes joining in this epoch.  `geo` optionally carries
    a whole placement profile in `GeoProfile.to_wire()` form so one admin
    contact installs the latency matrix cluster-wide.
    """

    type = MessageType.EPOCH_INSTALL_MSG
    replay_band = -1

    def __init__(self, epoch: int, shards: Tuple,
                 peers: Optional[Tuple] = None, geo=None):
        self.epoch = epoch
        self.shards = tuple(
            (int(s), int(e), tuple(int(n) for n in nodes))
            for s, e, nodes in shards)
        self.peers = (tuple(
            (int(p[0]), str(p[1]), int(p[2]))
            + ((str(p[3]),) if len(p) > 3 and p[3] else ())
            for p in peers) if peers else None)
        if geo is not None:
            from accord_tpu.topology.geo import GeoProfile
            if not isinstance(geo, GeoProfile):
                geo = GeoProfile.from_wire(geo)
            self.geo = geo.to_wire()  # canonical nested tuples
        else:
            self.geo = None

    @classmethod
    def from_topology(cls, topology, peers: Optional[Tuple] = None,
                      geo=None) -> "EpochInstall":
        return cls(topology.epoch,
                   tuple((s.range.start, s.range.end, s.sorted_nodes)
                         for s in topology.shards), peers, geo=geo)

    def build_topology(self):
        from accord_tpu.topology.topology import Topology
        from accord_tpu.topology.shard import Shard
        return Topology(self.epoch,
                        [Shard(Range(s, e), nodes)
                         for s, e, nodes in self.shards])

    def process(self, node, from_id: int, reply_context) -> None:
        service = getattr(node, "config_service", None)
        if service is not None:
            service.on_epoch_install(self, from_id)
        elif not node.topology.has_epoch(self.epoch):
            node.on_topology_update(self.build_topology())

    def __repr__(self):
        return f"EpochInstall(epoch={self.epoch}, shards={len(self.shards)})"


class TopologyFetchReq(Request):
    """Gap fetch: ask a peer for the EpochInstall spec of one epoch (the
    transport realization of the config service's fetch hook)."""

    type = MessageType.TOPOLOGY_FETCH_REQ

    def __init__(self, epoch: int):
        self.epoch = epoch

    def process(self, node, from_id: int, reply_context) -> None:
        service = getattr(node, "config_service", None)
        spec = service.spec_for(self.epoch) if service is not None else None
        if spec is None:
            node.reply(from_id, reply_context, TopologyFetchNack(self.epoch))
        else:
            node.reply(from_id, reply_context, TopologyFetchOk(spec))

    def __repr__(self):
        return f"TopologyFetchReq(epoch={self.epoch})"


class TopologyFetchOk(Reply):
    type = MessageType.TOPOLOGY_FETCH_RSP

    def __init__(self, install: EpochInstall):
        self.install = install

    def __repr__(self):
        return f"TopologyFetchOk({self.install!r})"


class TopologyFetchNack(Reply):
    type = MessageType.TOPOLOGY_FETCH_RSP

    def __init__(self, epoch: int):
        self.epoch = epoch

    def __repr__(self):
        return f"TopologyFetchNack(epoch={self.epoch})"


class DrainBegin(Request):
    """Scale-in step 1: `node_id` stops accepting NEW client coordination.
    Self-receipt fences the coordinator door; peer receipt deprioritizes the
    draining node as a bootstrap/fetch source.  Journaled, so a crashed
    drainer comes back still fenced."""

    type = MessageType.DRAIN_BEGIN_MSG
    replay_band = -1

    def __init__(self, node_id: int):
        self.node_id = node_id

    def process(self, node, from_id: int, reply_context) -> None:
        if node.id == self.node_id:
            node.draining = True
        node.draining_peers.add(self.node_id)
        node.obs.flight.record("drain_begin", None, (self.node_id, from_id))

    def __repr__(self):
        return f"DrainBegin(n{self.node_id})"


class DrainDone(Request):
    """Scale-in step 2 marker: `node_id` has handed off in-flight work and
    its durability watermarks cover its ranges — it can retire without
    losing an acked write."""

    type = MessageType.DRAIN_DONE_MSG
    replay_band = -1

    def __init__(self, node_id: int):
        self.node_id = node_id

    def process(self, node, from_id: int, reply_context) -> None:
        if node.id == self.node_id:
            node.drained = True
        node.draining_peers.add(self.node_id)
        node.obs.flight.record("drain_done", None, (self.node_id, from_id))

    def __repr__(self):
        return f"DrainDone(n{self.node_id})"


class BootstrapCheckpoint(Request):
    """WAL-only bootstrap progress record: the finalized coverage of one
    fetch attempt, with the installed snapshot and conflict watermarks.
    Written by Bootstrap._on_max_conflict as sub-ranges flip safe-to-read;
    `process()` runs only on crash-restart replay and re-installs exactly
    what the live path had finalized, so resume never re-fetches it.

    The fence TxnId is deliberately stored as `fence`, NOT `txn_id`: the
    compaction fold groups by `txn_id` and could subsume a record carrying
    one; `no_txn` records are always preserved verbatim."""

    type = MessageType.BOOTSTRAP_CHECKPOINT_MSG
    replay_band = -1

    def __init__(self, epoch: int, fence, ranges: Ranges, snapshot,
                 max_conflict=None, max_applied=None):
        self.epoch = epoch
        self.fence = fence
        self.ranges = ranges
        self.snapshot = snapshot
        self.max_conflict = max_conflict
        self.max_applied = max_applied

    def process(self, node, from_id: int, reply_context) -> None:
        from accord_tpu.local import commands as C
        from accord_tpu.local.store import PreLoadContext
        if self.snapshot:
            node.data_store.install_snapshot(self.snapshot)
        if self.max_applied is not None:
            node.on_remote_timestamp(self.max_applied)
        if self.max_conflict is not None:
            node.on_remote_timestamp(self.max_conflict)
        for store in node.command_stores.intersecting(self.ranges):
            owned = self.ranges.slice(store.ranges)
            if owned.is_empty:
                continue
            store.redundant_before.set_bootstrapped_at(owned, self.fence)
            if self.max_conflict is not None:
                store.max_conflicts.update(owned, self.max_conflict)
            store.mark_safe_to_read(owned)
            store.execute(PreLoadContext.empty(), C.re_evaluate_waiting)
        done = getattr(node, "_ckpt_bootstrapped", None)
        if done is not None:
            have = done.get(self.epoch, Ranges.EMPTY)
            done[self.epoch] = have.union(self.ranges)

    def __repr__(self):
        return (f"BootstrapCheckpoint(epoch={self.epoch}, "
                f"ranges={self.ranges!r})")


class BootstrapDone(Request):
    """WAL-only completion marker: every range this node was assigned in
    `epoch` finished bootstrapping (the sync-complete broadcast went out)."""

    type = MessageType.BOOTSTRAP_DONE_MSG
    replay_band = -1

    def __init__(self, epoch: int, ranges: Ranges):
        self.epoch = epoch
        self.ranges = ranges

    def process(self, node, from_id: int, reply_context) -> None:
        done = getattr(node, "_bootstrap_complete", None)
        if done is not None:
            done.add(self.epoch)

    def __repr__(self):
        return f"BootstrapDone(epoch={self.epoch})"
