"""Spill-tier record kinds for the bounded-memory command store.

Reference: accord's pluggable storage contract (accord/api/Journal.java +
accord-core's CommandStore persistence seams): command state a node cannot
afford to keep resident is durably *representable*, so an implementation may
evict and reload it without the protocol observing a missing command.

Two record kinds live here, both written ONLY to the pager's per-incarnation
spill store (`journal/fault_index.py`) — never the node WAL:

  * SpillFrame — the full quiescent payload of one evicted `Command`
    (local/paging.py writes one per eviction; a fault reads exactly one
    back via the fault index's (segment, offset) point-read).
  * FaultIndexCheckpoint — a periodic snapshot of the fault index itself,
    appended to the spill store so reopening it can seed the index from the
    latest checkpoint and scan only the frames appended after it, instead
    of re-scanning every segment.

Unlike the admin records (messages/admin.py), SpillFrame DOES carry a
`txn_id` attribute — that is safe here precisely because these records are
barred from the WAL and therefore from the snapshot-compaction fold that
groups by `txn_id` (both verbs register `has_side_effects=False`, so the
live journal path never frames one; `process()` is a loud no-op in case a
future path miswires them).  The spill store is scratch state: a restart
wipes it and WAL replay rebuilds residency from scratch.
"""

from __future__ import annotations

from typing import Optional, Tuple

from accord_tpu.messages.base import MessageType, Request


class SpillFrame(Request):
    """The evictable payload of one quiescent Command.

    Field-for-field the durable subset of `Command.__slots__`: listeners /
    transient_listeners are empty and `waiting_on` is None on any command
    the pager deems evictable (quiescent, decided), and `owned_keys_memo`
    is a pure cache — none of the four is carried, all four are recreated
    empty on refault (local/paging.py rebuilds via `to_command`)."""

    type = MessageType.SPILL_FRAME_MSG

    FIELDS = ("txn_id", "save_status", "durability", "route", "partial_txn",
              "execute_at", "execute_at_least", "promised", "accepted_ballot",
              "partial_deps", "stable_deps", "writes", "result")

    def __init__(self, txn_id, save_status, durability, route, partial_txn,
                 execute_at, execute_at_least, promised, accepted_ballot,
                 partial_deps, stable_deps, writes, result):
        self.txn_id = txn_id
        self.save_status = save_status
        self.durability = durability
        self.route = route
        self.partial_txn = partial_txn
        self.execute_at = execute_at
        self.execute_at_least = execute_at_least
        self.promised = promised
        self.accepted_ballot = accepted_ballot
        self.partial_deps = partial_deps
        self.stable_deps = stable_deps
        self.writes = writes
        self.result = result

    @classmethod
    def from_command(cls, cmd) -> "SpillFrame":
        return cls(*(getattr(cmd, f) for f in cls.FIELDS))

    def to_command(self):
        from accord_tpu.local.command import Command
        cmd = Command(self.txn_id)
        for f in self.FIELDS[1:]:
            setattr(cmd, f, getattr(self, f))
        return cmd

    def process(self, node, from_id: int, reply_context) -> None:
        raise AssertionError(
            "SpillFrame is a spill-store record; it must never be "
            "dispatched through the protocol or WAL-replay path")

    def __repr__(self):
        return f"SpillFrame({self.txn_id}, {self.save_status.name})"


class FaultIndexCheckpoint(Request):
    """Periodic snapshot of the spill store's fault index.

    `entries` is a portable tuple of (msb, lsb, node, segment_index,
    offset) rows — one per spilled command — matching TxnId.pack() so the
    checkpoint never holds live key objects.  `through_segment` /
    `through_offset` mark the append position the snapshot covers: a
    reopen seeds the index from the newest intact checkpoint and replays
    only frames past that position."""

    type = MessageType.FAULT_INDEX_CHECKPOINT_MSG

    def __init__(self, entries: Tuple, through_segment: int,
                 through_offset: int):
        self.entries = tuple(tuple(int(x) for x in row) for row in entries)
        self.through_segment = int(through_segment)
        self.through_offset = int(through_offset)

    def process(self, node, from_id: int, reply_context) -> None:
        raise AssertionError(
            "FaultIndexCheckpoint is a spill-store record; it must never "
            "be dispatched through the protocol or WAL-replay path")

    def __repr__(self):
        return (f"FaultIndexCheckpoint({len(self.entries)} entries, "
                f"through={self.through_segment}:{self.through_offset})")
