"""BeginInvalidation: the multi-shard invalidation voting round.

Reference: accord/messages/BeginInvalidation.java — each replica promises the
invalidation ballot (Commands.preacceptInvalidate) and reports everything it
knows: promise outcome, accepted ballot, status, whether it witnessed the txn
at its original timestamp (a fast-path accept), and any route fragment. The
coordinator (coordinate/invalidate.Invalidate) combines the per-shard votes
through InvalidationTracker to decide between invalidating outright and
escalating to recovery with the discovered route.
"""

from __future__ import annotations

from typing import Optional

from accord_tpu.local import commands as C
from accord_tpu.local.status import SaveStatus
from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.primitives.keys import Route
from accord_tpu.primitives.timestamp import Ballot, TxnId


class BeginInvalidation(TxnRequest):
    """Ask each replica to promise `ballot` toward invalidating txn_id and
    report its knowledge (BeginInvalidation.java:35-112)."""

    type = MessageType.BEGIN_INVALIDATE_REQ

    def __init__(self, txn_id: TxnId, scope: Route, ballot: Ballot):
        super().__init__(txn_id, scope)
        self.ballot = ballot

    def apply(self, safe_store) -> "InvalidateReply":
        promised = C.preaccept_invalidate(safe_store, self.txn_id, self.ballot)
        cmd = safe_store.get(self.txn_id)
        # this replica could only have cast a fast-path vote if it witnessed
        # the txn at its original timestamp (BeginInvalidation.java:66)
        accepted_fast_path = (cmd.execute_at is not None
                              and cmd.execute_at == self.txn_id.as_timestamp())
        superseded_by = None if promised else cmd.promised
        return InvalidateReply(superseded_by, cmd.accepted_ballot,
                               cmd.save_status, accepted_fast_path, cmd.route)

    def reduce(self, a: "InvalidateReply", b: "InvalidateReply"
               ) -> "InvalidateReply":
        """Collapse per-store replies into one pan-node answer: the node
        promises only if every store promised (a single store's reject means
        a competing ballot is live on this node), fast-path accept only if
        every store witnessed at original (BeginInvalidation.java:72-85)."""
        is_ok = a.is_promised and b.is_promised
        superseded_by = None
        if not is_ok:
            cands = [r.superseded_by for r in (a, b)
                     if r.superseded_by is not None]
            superseded_by = max(cands) if cands else None
        hi = a if (a.status, a.accepted) >= (b.status, b.accepted) else b
        route = (a.route.with_(b.route) if a.route is not None
                 and b.route is not None else a.route or b.route)
        return InvalidateReply(superseded_by, hi.accepted, hi.status,
                               a.accepted_fast_path and b.accepted_fast_path,
                               route)

    def __repr__(self):
        return f"BeginInvalidation({self.txn_id!r}, b={self.ballot!r})"


class InvalidateReply(Reply):
    """BeginInvalidation.InvalidateReply."""

    type = MessageType.BEGIN_INVALIDATE_RSP

    __slots__ = ("superseded_by", "accepted", "status", "accepted_fast_path",
                 "route")

    def __init__(self, superseded_by: Optional[Ballot], accepted: Ballot,
                 status: SaveStatus, accepted_fast_path: bool,
                 route: Optional[Route]):
        self.superseded_by = superseded_by
        self.accepted = accepted
        self.status = status
        self.accepted_fast_path = accepted_fast_path
        self.route = route

    @property
    def is_promised(self) -> bool:
        return self.superseded_by is None

    @property
    def has_decision(self) -> bool:
        """The txn is decided — executeAt (or invalidation) is durable."""
        return self.status >= SaveStatus.PRE_COMMITTED

    def __repr__(self):
        tag = "Promised" if self.is_promised else f"NotPromised({self.superseded_by!r})"
        return f"InvalidateReply({tag}, {self.status.name})"

    @staticmethod
    def find_full_route(replies) -> Optional[Route]:
        for r in replies:
            if r.route is not None and r.route.is_full:
                return r.route
        return None

    @staticmethod
    def merge_routes(replies) -> Optional[Route]:
        merged: Optional[Route] = None
        for r in replies:
            if r.route is None:
                continue
            merged = r.route if merged is None else merged.with_(r.route)
        return merged

    @staticmethod
    def max(replies) -> "InvalidateReply":
        return max(replies, key=lambda r: (r.status, r.accepted))
