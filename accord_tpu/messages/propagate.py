"""Propagate: apply knowledge learned remotely to the local stores.

Reference: accord/messages/Propagate.java:62 — a LOCAL request (never crosses
the network) that walks a merged CheckStatusOk into the local command state:
invalidation first, then outcome (apply), then stable deps (commit), then
executeAt (precommit), then the definition (preaccept). Each tier only fires
if the remote knowledge actually exceeds what this store already has; the
regular transition functions enforce monotonicity.
"""

from __future__ import annotations

from typing import Optional

from accord_tpu.local import commands as C
from accord_tpu.local.status import KnownDefinition, KnownDeps, SaveStatus
from accord_tpu.messages.base import MessageType, Reply, SimpleReply, TxnRequest
from accord_tpu.messages.checkstatus import CheckStatusOk
from accord_tpu.primitives.keys import Route
from accord_tpu.primitives.timestamp import TxnId


class Propagate(TxnRequest):
    type = MessageType.PROPAGATE_OTHER_MSG

    def __init__(self, txn_id: TxnId, scope: Route, known: CheckStatusOk):
        super().__init__(txn_id, scope)
        self.known = known

    def process(self, node, from_id, reply_context) -> None:
        node.map_reduce_consume_local(self, from_id, None)

    def apply(self, safe_store) -> Reply:
        k = self.known
        cmd = safe_store.get(self.txn_id)
        route = k.route if k.route is not None else self.route

        if k.save_status == SaveStatus.INVALIDATED:
            C.commit_invalidate(safe_store, self.txn_id)
            return SimpleReply(SimpleReply.OK)
        from accord_tpu.coordinate.infer import full_infer_enabled
        if k.save_status.is_truncated \
                and (k.writes is None or k.execute_at is None
                     or (full_infer_enabled()
                         and self.txn_id.kind.is_read)):
            # remote state is durably decided+applied and SHED, with no
            # outcome this store could still need: an erased write, or a
            # read — whose retained Writes object is vacuous, yet used to
            # route it into the apply tier where its erased deps struck
            # endless INSUFFICIENT catch-ups (a read below the fence can
            # never execute here and has nothing to install).  Full Infer
            # ladder: install the truncation locally (Infer.safeToCleanup
            # via Propagate in the reference) so local waiters stop
            # chasing it — the fence-refusal rule means our undecided
            # copy can never decide it either.  Under ACCORD_INFER_FULL=0
            # this stays the documented narrowing: nothing to learn from
            # an outcome-less truncation, and truncated reads keep
            # routing through the apply tier's INSUFFICIENT staleness
            # strikes.
            if full_infer_enabled() and not cmd.save_status.is_decided:
                C.set_truncated_remotely(safe_store, self.txn_id,
                                         k.execute_at)
            return SimpleReply(SimpleReply.OK)

        # what the merged reply actually justifies for THIS store's slice of
        # the route (CheckStatus.FoundKnownMap.knownFor): a partial-quorum
        # merge may carry a high global save_status whose definition/deps
        # fields cover only the shards that replied — slicing those to our
        # ranges would silently yield under-covering deps/bodies, so each
        # per-range tier below also requires the per-range knowledge
        owned = route.owned_participants(safe_store.ranges)
        knows = k.known_for(owned)

        local = k.partial_txn.slice(safe_store.ranges, include_query=False) \
            if k.partial_txn is not None and not safe_store.ranges.is_empty \
            else k.partial_txn
        deps = k.stable_deps.slice(safe_store.ranges) \
            if k.stable_deps is not None and not safe_store.ranges.is_empty \
            else k.stable_deps
        if knows.deps < KnownDeps.STABLE:
            # not justified for every owned range: let each tier's
            # deps-required path degrade (apply falls to INSUFFICIENT
            # catch-up + staleness escalation, commit tiers are skipped)
            deps = None
        if knows.definition < KnownDefinition.YES:
            local = None

        if k.save_status >= SaveStatus.PRE_APPLIED and k.writes is not None \
                and k.execute_at is not None:
            outcome = C.apply(safe_store, self.txn_id, route, k.execute_at,
                              deps, k.writes, k.result, partial_txn=local)
            if outcome != C.ApplyOutcome.INSUFFICIENT:
                safe_store.store.insufficient_catchups.pop(self.txn_id, None)
            elif knows.deps == KnownDeps.ERASED:
                # truncated-with-outcome source (deps purged, gone forever)
                # and we are below STABLE: per-txn catch-up cannot order
                # this write safely — applying here with fabricated deps
                # could reorder writes under the data plane's executeAt
                # guard. After repeated failures, declare the owning ranges
                # stale and re-acquire them wholesale (reference
                # markShardStale -> bootstrap; ADVICE r1: nothing else
                # triggers bootstrap outside topology changes, so the
                # replica wedged forever).
                self._maybe_escalate_staleness(safe_store, route)
            # else: deps merely unfetched (partial quorum, partition) — a
            # later fetch can still supply them, so no escalation strike
            return SimpleReply(SimpleReply.OK)
        if k.save_status >= SaveStatus.STABLE and k.execute_at is not None \
                and deps is not None and not cmd.has_been(SaveStatus.STABLE):
            C.commit(safe_store, self.txn_id, route, local, k.execute_at,
                     deps, stable=True)
            return SimpleReply(SimpleReply.OK)
        if k.save_status >= SaveStatus.COMMITTED and k.execute_at is not None \
                and deps is not None and not cmd.has_been(SaveStatus.COMMITTED):
            C.commit(safe_store, self.txn_id, route, local, k.execute_at,
                     deps, stable=False)
            return SimpleReply(SimpleReply.OK)
        if k.save_status >= SaveStatus.PRE_COMMITTED \
                and k.execute_at is not None \
                and not cmd.has_been(SaveStatus.PRE_COMMITTED):
            C.precommit(safe_store, self.txn_id, k.execute_at)
            return SimpleReply(SimpleReply.OK)
        if k.save_status >= SaveStatus.PRE_ACCEPTED and local is not None \
                and not cmd.has_been(SaveStatus.PRE_ACCEPTED):
            C.preaccept(safe_store, self.txn_id, local, route)
            return SimpleReply(SimpleReply.OK)
        return SimpleReply(SimpleReply.OK)

    STALE_AFTER_ATTEMPTS = 3

    def _maybe_escalate_staleness(self, safe_store, route: Route) -> None:
        """After repeated INSUFFICIENT catch-ups, mark the owning ranges stale
        and drive a bootstrap fetch for them (Agent.onStale / markShardStale
        -> Bootstrap in the reference)."""
        store = safe_store.store
        count = store.insufficient_catchups.get(self.txn_id, 0) + 1
        store.insufficient_catchups[self.txn_id] = count
        if count < self.STALE_AFTER_ATTEMPTS:
            return
        store.insufficient_catchups.pop(self.txn_id, None)
        covering = route.covering() if route is not None else None
        if covering is None or covering.is_empty:
            return
        owned = covering.slice(store.ranges) \
            if not store.ranges.is_empty else covering
        if owned.is_empty:
            return
        stale_until = self.known.execute_at if self.known.execute_at \
            is not None else self.txn_id
        store.redundant_before.set_stale_until(owned, stale_until)
        # a stale span must nack reads immediately (coordinator retries a
        # healthy peer) rather than let them hang on never-applying deps;
        # Bootstrap._finish restores safe_to_read once the snapshot lands
        store.safe_to_read = store.safe_to_read.subtract(owned)
        safe_store.node.mark_stale_and_bootstrap(owned)

    def reduce(self, a, b):
        return a

    def __repr__(self):
        return f"Propagate({self.txn_id!r}, {self.known.save_status.name})"
