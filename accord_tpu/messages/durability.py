"""Durability gossip verbs.

Reference: accord/messages/InformDurable.java, SetShardDurable.java,
SetGloballyDurable.java, QueryDurableBefore.java, InformOfTxnId.java —
distribute per-txn durability class and the DurableBefore watermarks that
license truncation (SURVEY.md §2.4 registry).
"""

from __future__ import annotations

from typing import Optional, Tuple

from accord_tpu.local import commands as C
from accord_tpu.local.status import Durability
from accord_tpu.messages.base import (MessageType, Reply, Request,
                                      SimpleReply, TxnRequest)
from accord_tpu.primitives.keys import Ranges, Route
from accord_tpu.primitives.timestamp import TxnId, TXNID_NONE


class InformDurable(TxnRequest):
    """Mark a txn's durability class on its participants
    (InformDurable.java; sent by the Persist tail once a quorum per shard
    acked Apply)."""

    type = MessageType.INFORM_DURABLE_REQ

    def __init__(self, txn_id: TxnId, scope: Route, durability: Durability):
        super().__init__(txn_id, scope)
        self.durability = durability

    def apply(self, safe_store) -> Reply:
        C.set_durability(safe_store, self.txn_id, self.durability)
        return SimpleReply(SimpleReply.OK)

    def reduce(self, a, b):
        return a

    def __repr__(self):
        return f"InformDurable({self.txn_id!r}, {self.durability.name})"


class InformHomeDurable(TxnRequest):
    """Tell the HOME shard a txn is durable so its progress-log monitor
    stands down without waiting to observe durability itself (reference
    accord/messages/InformHomeDurable.java:30: set the durability class at
    the home key's store, skipping truncated commands).  Sent by a
    non-home replica whose blocked-state chase learns a durable outcome
    (impl/progress_log.py) — the home-specific short-circuit on top of the
    participant-wide InformDurable the Persist tail broadcasts."""

    type = MessageType.INFORM_HOME_DURABLE_REQ

    def __init__(self, txn_id: TxnId, scope: Route, execute_at,
                 durability: Durability):
        super().__init__(txn_id, scope)
        self.execute_at = execute_at
        self.durability = durability

    def apply(self, safe_store) -> Reply:
        cmd = safe_store.get(self.txn_id)
        if cmd.is_truncated:
            return SimpleReply(SimpleReply.OK)
        C.set_durability(safe_store, self.txn_id, self.durability)
        return SimpleReply(SimpleReply.OK)

    def reduce(self, a, b):
        return a

    def __repr__(self):
        return f"InformHomeDurable({self.txn_id!r}, {self.durability.name})"


class InformOfTxnId(TxnRequest):
    """Make sure the home shard knows a txn exists, so its progress log
    monitors it (InformOfTxnId.java / InformHomeOfTxn)."""

    type = MessageType.INFORM_OF_TXN_REQ

    def __init__(self, txn_id: TxnId, scope: Route):
        super().__init__(txn_id, scope)

    def apply(self, safe_store) -> Reply:
        cmd = safe_store.get(self.txn_id)
        cmd.update_route(self.route)
        safe_store.progress_log.update(safe_store.store, self.txn_id, cmd)
        return SimpleReply(SimpleReply.OK)

    def reduce(self, a, b):
        return a

    def __repr__(self):
        return f"InformOfTxnId({self.txn_id!r})"


class SetShardDurable(TxnRequest):
    """An exclusive sync point's fence is durable: everything on its ranges
    below it is decided+applied at (majority | every) replica — advance the
    DurableBefore watermark and sweep (SetShardDurable.java)."""

    type = MessageType.SET_SHARD_DURABLE_REQ

    def __init__(self, txn_id: TxnId, scope: Route, ranges: Ranges,
                 universal: bool):
        super().__init__(txn_id, scope)
        self.ranges = ranges
        self.universal = universal

    def apply(self, safe_store) -> Reply:
        from accord_tpu.local import cleanup
        store = safe_store.store
        owned = self.ranges.slice(store.ranges) \
            if not store.ranges.is_empty else self.ranges
        if self.universal:
            store.durable_before.update(owned, self.txn_id, self.txn_id)
            # every replica applied the fence: undecided stragglers below it
            # can never commit — poison them (shardAppliedBefore gating)
            store.redundant_before.update_shard_applied(owned, self.txn_id)
        else:
            store.durable_before.update(owned, self.txn_id)
        cleanup.sweep(store)
        return SimpleReply(SimpleReply.OK)

    def reduce(self, a, b):
        return a

    def __repr__(self):
        return (f"SetShardDurable({self.txn_id!r} over {self.ranges!r}, "
                f"universal={self.universal})")


class QueryDurableBeforeOk(Reply):
    type = MessageType.QUERY_DURABLE_BEFORE_RSP

    def __init__(self, majority: TxnId, universal: TxnId):
        self.majority = majority
        self.universal = universal

    def __repr__(self):
        return f"QueryDurableBeforeOk(maj<{self.majority!r}, uni<{self.universal!r})"


class QueryDurableBefore(TxnRequest):
    """Report this node's floor DurableBefore bounds over `ranges`
    (QueryDurableBefore.java; min-merged by CoordinateGloballyDurable)."""

    type = MessageType.QUERY_DURABLE_BEFORE_REQ

    def __init__(self, txn_id: TxnId, scope: Route, ranges: Ranges):
        super().__init__(txn_id, scope)
        self.ranges = ranges

    def apply(self, safe_store) -> Reply:
        store = safe_store.store
        owned = self.ranges.slice(store.ranges) \
            if not store.ranges.is_empty else self.ranges
        if owned.is_empty:
            return QueryDurableBeforeOk(TXNID_NONE, TXNID_NONE)
        maj, uni = store.durable_before.min_bounds(owned)
        return QueryDurableBeforeOk(maj, uni)

    def reduce(self, a: QueryDurableBeforeOk, b: QueryDurableBeforeOk):
        return QueryDurableBeforeOk(min(a.majority, b.majority),
                                    min(a.universal, b.universal))

    def __repr__(self):
        return f"QueryDurableBefore({self.ranges!r})"


class SetGloballyDurable(TxnRequest):
    """Adopt a globally min-merged DurableBefore over `ranges`
    (SetGloballyDurable.java) — licenses ERASE."""

    type = MessageType.SET_GLOBALLY_DURABLE_REQ

    def __init__(self, txn_id: TxnId, scope: Route, ranges: Ranges,
                 majority: TxnId, universal: TxnId):
        super().__init__(txn_id, scope)
        self.ranges = ranges
        self.majority = majority
        self.universal = universal

    def apply(self, safe_store) -> Reply:
        from accord_tpu.local import cleanup
        store = safe_store.store
        owned = self.ranges.slice(store.ranges) \
            if not store.ranges.is_empty else self.ranges
        if not owned.is_empty and (self.majority > TXNID_NONE
                                   or self.universal > TXNID_NONE):
            store.durable_before.update(owned, self.majority, self.universal)
            cleanup.sweep(store)
        return SimpleReply(SimpleReply.OK)

    def reduce(self, a, b):
        return a

    def __repr__(self):
        return f"SetGloballyDurable({self.ranges!r} maj<{self.majority!r})"
