"""Accept: slow-path ballot acceptance of (executeAt, deps).

Reference: accord/messages/Accept.java:50 — Commands.accept then a fresh deps
calculation bounded by executeAt, returned for the commit round (:84-130);
inner Accept.Invalidate.
"""

from __future__ import annotations

from accord_tpu.local import commands as C
from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Keys, Route
from accord_tpu.primitives.timestamp import Ballot, Timestamp, TxnId


class AcceptOk(Reply):
    type = MessageType.ACCEPT_RSP

    def __init__(self, txn_id: TxnId, deps: Deps):
        self.txn_id = txn_id
        self.deps = deps

    def __repr__(self):
        return f"AcceptOk({self.txn_id!r})"


class AcceptNack(Reply):
    type = MessageType.ACCEPT_RSP

    def __init__(self, reason: C.AcceptOutcome):
        self.reason = reason

    def __repr__(self):
        return f"AcceptNack({self.reason.name})"


class Accept(TxnRequest):
    type = MessageType.ACCEPT_REQ

    def __init__(self, txn_id: TxnId, ballot: Ballot, scope: Route,
                 participating_keys, execute_at: Timestamp, deps: Deps,
                 max_epoch: int = 0, full_route: Route = None):
        super().__init__(txn_id, scope,
                         wait_for_epoch=max_epoch or execute_at.epoch,
                         full_route=full_route)
        self.ballot = ballot
        self.participating_keys = participating_keys
        self.execute_at = execute_at
        self.deps = deps

    def apply(self, safe_store) -> Reply:
        owned_keys = self.participating_keys.slice(safe_store.ranges) \
            if not safe_store.ranges.is_empty else self.participating_keys
        outcome = C.accept(safe_store, self.txn_id, self.ballot, self.route,
                           owned_keys, self.execute_at,
                           self.deps.slice(safe_store.ranges))
        if outcome in (C.AcceptOutcome.SUCCESS, C.AcceptOutcome.REDUNDANT):
            # deps freshly calculated up to executeAt for the commit round.
            # The REDUNDANT (already PRE_COMMITTED+) arm must ALSO report its
            # known conflicts: this reply still counts toward the accept
            # quorum, and a conflict known only to this replica would
            # otherwise be missing from the stable-deps union
            deps = C.calculate_deps(safe_store, self.txn_id, owned_keys,
                                    before=self.execute_at)
            return AcceptOk(self.txn_id, deps)
        return AcceptNack(outcome)

    def deps_probe(self):
        return (self.execute_at, self.txn_id.kind.witnesses(),
                self.participating_keys)

    def reduce(self, a: Reply, b: Reply) -> Reply:
        if isinstance(a, AcceptNack):
            return a
        if isinstance(b, AcceptNack):
            return b
        assert isinstance(a, AcceptOk) and isinstance(b, AcceptOk)
        return AcceptOk(self.txn_id, a.deps.with_(b.deps))

    def __repr__(self):
        return f"Accept({self.txn_id!r}@{self.execute_at!r}, b={self.ballot!r})"


class AcceptInvalidate(TxnRequest):
    """Accept.Invalidate: promise at `ballot` to invalidate txn_id."""

    type = MessageType.ACCEPT_INVALIDATE_REQ

    def __init__(self, txn_id: TxnId, ballot: Ballot, scope: Route):
        super().__init__(txn_id, scope)
        self.ballot = ballot

    def apply(self, safe_store) -> Reply:
        outcome = C.accept_invalidate(safe_store, self.txn_id, self.ballot)
        if outcome in (C.AcceptOutcome.SUCCESS, C.AcceptOutcome.REDUNDANT):
            return AcceptOk(self.txn_id, Deps.NONE)
        return AcceptNack(outcome)

    def reduce(self, a: Reply, b: Reply) -> Reply:
        if isinstance(a, AcceptNack):
            return a
        return b
