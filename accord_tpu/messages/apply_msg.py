"""Apply: deliver the outcome (writes + result) for asynchronous persistence.

Reference: accord/messages/Apply.java:47 — Kinds Minimal/Maximal (:72);
Commands.apply then reply Applied/Redundant/Insufficient (:146-210).
"""

from __future__ import annotations

import enum
from typing import Optional

from accord_tpu.local import commands as C
from accord_tpu.messages.base import MessageType, Reply, TxnRequest
from accord_tpu.primitives.deps import Deps
from accord_tpu.primitives.keys import Route
from accord_tpu.primitives.timestamp import Timestamp, TxnId
from accord_tpu.primitives.txn import PartialTxn
from accord_tpu.primitives.writes import Writes


class ApplyReply(Reply):
    type = MessageType.APPLY_RSP

    APPLIED = "Applied"
    REDUNDANT = "Redundant"
    INSUFFICIENT = "Insufficient"

    def __init__(self, outcome: str):
        self.outcome = outcome

    def __eq__(self, other):
        return isinstance(other, ApplyReply) and self.outcome == other.outcome

    def __repr__(self):
        return f"ApplyReply({self.outcome})"


class ApplyKind(enum.Enum):
    MINIMAL = MessageType.APPLY_MINIMAL_REQ
    MAXIMAL = MessageType.APPLY_MAXIMAL_REQ


class Apply(TxnRequest):
    def __init__(self, kind: ApplyKind, txn_id: TxnId, scope: Route,
                 execute_at: Timestamp, deps: Optional[Deps],
                 writes: Optional[Writes], result,
                 partial_txn: Optional[PartialTxn] = None,
                 full_route: Route = None):
        super().__init__(txn_id, scope, wait_for_epoch=execute_at.epoch,
                         full_route=full_route)
        self.kind = kind
        self.type = kind.value
        self.execute_at = execute_at
        self.deps = deps
        self.writes = writes
        self.result = result
        self.partial_txn = partial_txn  # Maximal only

    def apply(self, safe_store):
        deps = self.deps
        if deps is not None and not safe_store.ranges.is_empty:
            deps = deps.slice(safe_store.ranges)
        # store the FULL writes (reference keeps command.writes() unsliced;
        # execution slices per store via Writes.apply(within)): outcome
        # knowledge is then legitimately global — any replica that knows the
        # outcome can hand every store the whole effect, so CheckStatus
        # merges need no per-range writes provenance
        outcome = C.apply(safe_store, self.txn_id, self.route, self.execute_at,
                          deps, self.writes, self.result,
                          partial_txn=self.partial_txn)
        return ApplyReply({
            C.ApplyOutcome.SUCCESS: ApplyReply.APPLIED,
            C.ApplyOutcome.REDUNDANT: ApplyReply.REDUNDANT,
            C.ApplyOutcome.INSUFFICIENT: ApplyReply.INSUFFICIENT,
        }[outcome])

    def reduce(self, a: ApplyReply, b: ApplyReply) -> ApplyReply:
        order = [ApplyReply.INSUFFICIENT, ApplyReply.APPLIED, ApplyReply.REDUNDANT]
        return a if order.index(a.outcome) <= order.index(b.outcome) else b

    def execute_probe(self):
        """The execution this Apply delivers, for the device store's
        in-window wavefront scheduler (reference execution ordering:
        Commands.maybeExecute :656 + NotifyWaitingOn :1011 walk one
        command at a time; the device plans the whole window's order in
        one kernel dispatch)."""
        if not self.scope.is_key_domain:
            return None  # range-domain executions stay on the scalar walk
        return (self.txn_id, self.execute_at, self.scope.participant_keys())

    def __repr__(self):
        return f"Apply({self.kind.name}, {self.txn_id!r}@{self.execute_at!r})"


class ApplyThenWaitUntilApplied(Apply):
    """Apply the outcome AND reply only once the command has applied
    locally — commit, (trivial) execute, and apply fused into one
    request/response (reference accord/messages/
    ApplyThenWaitUntilApplied.java:37, used by sync-point execution,
    coordinate/ExecuteSyncPoint.java:66).

    A sync point carries no writes; it reaches APPLIED exactly when its
    dependencies drain on this replica, so acking at APPLIED is the
    reference's "return when the dependencies are Applied" — and it saves
    the separate WaitUntilApplied round the unfused path pays (reference
    impl/AbstractFetchCoordinator.java:215 uses the same fusion on the
    bootstrap path).  An INSUFFICIENT outcome still nacks immediately so
    the coordinator can escalate to a maximal apply."""

    def __init__(self, kind: ApplyKind, txn_id: TxnId, scope: Route,
                 execute_at: Timestamp, deps: Optional[Deps],
                 writes: Optional[Writes], result,
                 partial_txn: Optional[PartialTxn] = None,
                 full_route: Route = None):
        super().__init__(kind, txn_id, scope, execute_at, deps, writes,
                         result, partial_txn=partial_txn,
                         full_route=full_route)
        self.type = MessageType.APPLY_THEN_WAIT_UNTIL_APPLIED_REQ

    def apply(self, safe_store):
        from accord_tpu.messages.wait import await_applied

        reply = super().apply(safe_store)
        if reply.outcome == ApplyReply.INSUFFICIENT:
            return reply
        return await_applied(safe_store, self.txn_id,
                             self.scope.participants(), reply)

    def __repr__(self):
        return (f"ApplyThenWaitUntilApplied({self.kind.name}, "
                f"{self.txn_id!r}@{self.execute_at!r})")
