"""WaitOnCommit: block until a txn is committed locally, then ack.

Reference: accord/messages/WaitOnCommit.java — registers a listener until the
command reaches Committed (or is invalidated/truncated), nudging the progress
log so the replica itself chases the missing commit. Used by recovery to await
`earlierAcceptedNoWitness` transactions before deciphering the fast path.
"""

from __future__ import annotations

from accord_tpu.local.command import Command, TransientListener
from accord_tpu.local.status import SaveStatus
from accord_tpu.messages.base import MessageType, SimpleReply, TxnRequest
from accord_tpu.primitives.keys import Route
from accord_tpu.primitives.timestamp import TxnId
from accord_tpu.utils.async_chains import AsyncResult


class _NotifyOnCommit(TransientListener):
    def __init__(self, result: AsyncResult):
        self.result = result
        self.done = False

    def on_change(self, safe_store, command: Command) -> None:
        self.maybe_fire(command)

    def maybe_fire(self, command: Command) -> None:
        if self.done:
            return
        if command.has_been(SaveStatus.COMMITTED) or command.is_invalidated \
                or command.is_truncated:
            self.done = True
            command.remove_transient_listener(self)
            self.result.try_success(SimpleReply(SimpleReply.OK))


def await_applied(safe_store, txn_id: TxnId, participants, reply):
    """Shared wait tail: resolve with `reply` once txn_id has APPLIED
    locally, nudging the progress log if it isn't even STABLE yet.  Used
    by WaitUntilApplied and the fused ApplyThenWaitUntilApplied."""
    from accord_tpu.local.command import OnAppliedListener
    command = safe_store.get(txn_id)
    result: AsyncResult = AsyncResult()
    listener = OnAppliedListener.arm(
        command, lambda c: result.try_success(reply))
    if not listener.fired and not command.has_been(SaveStatus.STABLE):
        safe_store.progress_log.waiting(
            txn_id, safe_store.store, "Applied", command.route, participants)
    return result


class WaitUntilApplied(TxnRequest):
    """Block until the txn has applied locally, then ack
    (accord/messages/WaitUntilApplied — WAIT_UNTIL_APPLIED_REQ). Used by
    durability rounds to confirm a sync point's dependencies drained on this
    replica."""

    type = MessageType.WAIT_UNTIL_APPLIED_REQ

    def __init__(self, txn_id: TxnId, scope: Route):
        super().__init__(txn_id, scope)

    def apply(self, safe_store):
        return await_applied(safe_store, self.txn_id,
                             self.scope.participants(),
                             SimpleReply(SimpleReply.OK))

    def reduce(self, a, b):
        return b

    def __repr__(self):
        return f"WaitUntilApplied({self.txn_id!r})"


class WaitOnCommit(TxnRequest):
    type = MessageType.WAIT_ON_COMMIT_REQ

    def __init__(self, txn_id: TxnId, scope: Route):
        super().__init__(txn_id, scope)

    def apply(self, safe_store):
        command = safe_store.get(self.txn_id)
        result: AsyncResult = AsyncResult()
        listener = _NotifyOnCommit(result)
        command.add_transient_listener(listener)
        listener.maybe_fire(command)
        if not listener.done:
            # chase the commit: the progress log fetches/recovers it
            safe_store.progress_log.waiting(
                self.txn_id, safe_store.store, "Committed", command.route,
                self.scope.participants())
        return result

    def reduce(self, a, b):
        return b

    def __repr__(self):
        return f"WaitOnCommit({self.txn_id!r})"
