/* Native sorted-array kernels — the framework's hottest host-side loops.
 *
 * Reference: accord/utils/SortedArrays.java:44 (linearUnion /
 * linearIntersection / linearSubtract and the binary-search family). These
 * run under every Keys/RoutingKeys/TxnId merge in the protocol engine, so
 * they get a C implementation mirroring accord_tpu/utils/sorted_arrays.py
 * exactly — including the identity-return convention of linear_union (one
 * input subsuming the other is returned as the SAME object so singleton
 * checks like KeyDeps.NONE keep working).
 *
 * Elements are arbitrary Python objects ordered via rich comparison (<),
 * exactly like the Python tier; comparison errors propagate.
 *
 * Built on first import by accord_tpu/native/__init__.py (g++ into a cached
 * shared object); everything falls back to the Python tier when no
 * toolchain is present.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

namespace {

/* a < b via rich comparison; -1 on error */
inline int lt(PyObject *a, PyObject *b) {
    return PyObject_RichCompareBool(a, b, Py_LT);
}

struct FastSeq {
    PyObject *seq = nullptr;
    PyObject **items = nullptr;
    Py_ssize_t n = 0;

    bool init(PyObject *obj) {
        seq = PySequence_Fast(obj, "expected a sequence");
        if (seq == nullptr) return false;
        items = PySequence_Fast_ITEMS(seq);
        n = PySequence_Fast_GET_SIZE(seq);
        return true;
    }
    ~FastSeq() { Py_XDECREF(seq); }
};

PyObject *linear_union(PyObject *, PyObject *args) {
    PyObject *ao, *bo;
    if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return nullptr;
    FastSeq a, b;
    if (!a.init(ao) || !b.init(bo)) return nullptr;
    if (a.n == 0) {
        if (PyList_Check(bo)) { Py_INCREF(bo); return bo; }
        return PySequence_List(bo);
    }
    if (b.n == 0) {
        if (PyList_Check(ao)) { Py_INCREF(ao); return ao; }
        return PySequence_List(ao);
    }
    PyObject *out = PyList_New(0);
    if (out == nullptr) return nullptr;
    Py_ssize_t i = 0, j = 0;
    while (i < a.n && j < b.n) {
        PyObject *x = a.items[i], *y = b.items[j];
        int xy = lt(x, y);
        if (xy < 0) goto fail;
        if (xy) {
            if (PyList_Append(out, x) < 0) goto fail;
            ++i;
        } else {
            int yx = lt(y, x);
            if (yx < 0) goto fail;
            if (yx) {
                if (PyList_Append(out, y) < 0) goto fail;
                ++j;
            } else {
                if (PyList_Append(out, x) < 0) goto fail;
                ++i; ++j;
            }
        }
    }
    for (; i < a.n; ++i)
        if (PyList_Append(out, a.items[i]) < 0) goto fail;
    for (; j < b.n; ++j)
        if (PyList_Append(out, b.items[j]) < 0) goto fail;
    return out;
fail:
    Py_DECREF(out);
    return nullptr;
}

PyObject *linear_intersection(PyObject *, PyObject *args) {
    PyObject *ao, *bo;
    if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return nullptr;
    FastSeq a, b;
    if (!a.init(ao) || !b.init(bo)) return nullptr;
    PyObject *out = PyList_New(0);
    if (out == nullptr) return nullptr;
    Py_ssize_t i = 0, j = 0;
    while (i < a.n && j < b.n) {
        PyObject *x = a.items[i], *y = b.items[j];
        int xy = lt(x, y);
        if (xy < 0) goto fail;
        if (xy) { ++i; continue; }
        int yx = lt(y, x);
        if (yx < 0) goto fail;
        if (yx) { ++j; continue; }
        if (PyList_Append(out, x) < 0) goto fail;
        ++i; ++j;
    }
    return out;
fail:
    Py_DECREF(out);
    return nullptr;
}

PyObject *linear_subtract(PyObject *, PyObject *args) {
    PyObject *ao, *bo;
    if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return nullptr;
    FastSeq a, b;
    if (!a.init(ao) || !b.init(bo)) return nullptr;
    PyObject *out = PyList_New(0);
    if (out == nullptr) return nullptr;
    Py_ssize_t i = 0, j = 0;
    while (i < a.n && j < b.n) {
        PyObject *x = a.items[i], *y = b.items[j];
        int xy = lt(x, y);
        if (xy < 0) goto fail;
        if (xy) {
            if (PyList_Append(out, x) < 0) goto fail;
            ++i; continue;
        }
        int yx = lt(y, x);
        if (yx < 0) goto fail;
        if (yx) { ++j; continue; }
        ++i; ++j;
    }
    for (; i < a.n; ++i)
        if (PyList_Append(out, a.items[i]) < 0) goto fail;
    return out;
fail:
    Py_DECREF(out);
    return nullptr;
}

/* binary_search(xs, target, lo=0, hi=None) -> match index or
 * -(insertion_point)-1, the Java convention the Python tier mirrors */
PyObject *binary_search(PyObject *, PyObject *args) {
    PyObject *xso, *target, *hio = Py_None;
    Py_ssize_t lo = 0;
    if (!PyArg_ParseTuple(args, "OO|nO", &xso, &target, &lo, &hio))
        return nullptr;
    FastSeq xs;
    if (!xs.init(xso)) return nullptr;
    Py_ssize_t hi = xs.n;
    if (hio != Py_None) {
        hi = PyNumber_AsSsize_t(hio, PyExc_OverflowError);
        if (hi == -1 && PyErr_Occurred()) return nullptr;
    }
    /* out-of-contract bounds raise exactly like the Python tier's xs[mid]
     * would — never read outside the item array */
    if (lo < 0 || hi > xs.n) {
        PyErr_SetString(PyExc_IndexError, "binary_search bounds outside sequence");
        return nullptr;
    }
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) / 2;
        PyObject *v = xs.items[mid];
        int vlt = lt(v, target);
        if (vlt < 0) return nullptr;
        if (vlt) { lo = mid + 1; continue; }
        int tlt = lt(target, v);
        if (tlt < 0) return nullptr;
        if (tlt) hi = mid;
        else return PyLong_FromSsize_t(mid);
    }
    return PyLong_FromSsize_t(-(lo + 1));
}

/* k-way union of sorted unique sequences: iterative pairwise merge run
 * entirely natively (the RelationMultiMap.LinearMerger id-pool union). */
PyObject *merge_two(PyObject *ao, PyObject *bo) {
    PyObject *args = PyTuple_Pack(2, ao, bo);
    if (args == nullptr) return nullptr;
    PyObject *out = linear_union(nullptr, args);
    Py_DECREF(args);
    return out;
}

PyObject *linear_merge_n(PyObject *, PyObject *args) {
    PyObject *listso;
    if (!PyArg_ParseTuple(args, "O", &listso)) return nullptr;
    FastSeq lists;
    if (!lists.init(listso)) return nullptr;
    if (lists.n == 0) return PyList_New(0);
    PyObject *acc = PySequence_List(lists.items[0]);
    if (acc == nullptr) return nullptr;
    for (Py_ssize_t i = 1; i < lists.n; ++i) {
        PyObject *next = merge_two(acc, lists.items[i]);
        Py_DECREF(acc);
        if (next == nullptr) return nullptr;
        acc = next;
    }
    return acc;
}

/* ---- CINTIA checkpoint-interval stabbing over int64 interval arrays ----
 * Mirrors accord_tpu/utils/checkpoint_intervals.py exactly (reference
 * CheckpointIntervalArray.java:28-84): same checkpoint layout, same visit
 * order. Values must fit int64; the Python tier handles anything wider.
 *
 * cintia_build converts once and returns an opaque capsule holding the
 * int64 arrays (intervals + checkpoint CSR); queries run against the
 * capsule with NO per-query marshalling — the O(lg N + K) contract holds
 * natively. */

struct Cintia {
    long long *starts = nullptr, *ends = nullptr;
    long long *offsets = nullptr, *entries = nullptr;
    Py_ssize_t n = 0, n_offsets = 0, n_entries = 0, every = 1;

    ~Cintia() {
        PyMem_Free(starts); PyMem_Free(ends);
        PyMem_Free(offsets); PyMem_Free(entries);
    }
};

void cintia_destroy(PyObject *capsule) {
    delete (Cintia *)PyCapsule_GetPointer(capsule, "accord.cintia");
}

long long *to_i64(PyObject *obj, Py_ssize_t *out_n) {
    FastSeq seq;
    if (!seq.init(obj)) return nullptr;
    *out_n = seq.n;
    long long *v = (long long *)PyMem_Malloc(
        sizeof(long long) * (seq.n ? seq.n : 1));
    if (v == nullptr) { PyErr_NoMemory(); return nullptr; }
    for (Py_ssize_t i = 0; i < seq.n; ++i) {
        long long x = PyLong_AsLongLong(seq.items[i]);
        if (x == -1 && PyErr_Occurred()) { PyMem_Free(v); return nullptr; }
        v[i] = x;
    }
    return v;
}

/* count of elements <= x (bisect_right) / < x (bisect_left) */
inline Py_ssize_t upper_bound(const long long *v, Py_ssize_t n, long long x) {
    Py_ssize_t lo = 0, hi = n;
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) / 2;
        if (v[mid] <= x) lo = mid + 1; else hi = mid;
    }
    return lo;
}
inline Py_ssize_t lower_bound(const long long *v, Py_ssize_t n, long long x) {
    Py_ssize_t lo = 0, hi = n;
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) / 2;
        if (v[mid] < x) lo = mid + 1; else hi = mid;
    }
    return lo;
}

PyObject *cintia_build(PyObject *, PyObject *args) {
    PyObject *so, *eo;
    Py_ssize_t every;
    if (!PyArg_ParseTuple(args, "OOn", &so, &eo, &every)) return nullptr;
    Cintia *c = new Cintia();
    c->every = every > 0 ? every : 1;
    Py_ssize_t n_ends = 0;
    c->starts = to_i64(so, &c->n);
    if (c->starts == nullptr) { delete c; return nullptr; }
    c->ends = to_i64(eo, &n_ends);
    if (c->ends == nullptr || n_ends != c->n) {
        delete c;
        if (!PyErr_Occurred())
            PyErr_SetString(PyExc_ValueError, "starts/ends length mismatch");
        return nullptr;
    }
    Py_ssize_t n_cp = c->n ? (c->n + c->every - 1) / c->every : 0;
    c->offsets = (long long *)PyMem_Malloc(
        sizeof(long long) * (n_cp ? n_cp : 1));
    if (c->offsets == nullptr) { delete c; PyErr_NoMemory(); return nullptr; }
    /* single pass; entries grows by doubling */
    Py_ssize_t cap = 16;
    c->entries = (long long *)PyMem_Malloc(sizeof(long long) * cap);
    if (c->entries == nullptr) { delete c; PyErr_NoMemory(); return nullptr; }
    Py_ssize_t e = 0, ci = 0;
    for (Py_ssize_t cp = 0; cp < c->n; cp += c->every) {
        if (cp > 0) {
            long long boundary = c->starts[cp];
            for (Py_ssize_t i = 0; i < cp; ++i) {
                if (c->ends[i] > boundary) {
                    if (e == cap) {
                        cap *= 2;
                        long long *grown = (long long *)PyMem_Realloc(
                            c->entries, sizeof(long long) * cap);
                        if (grown == nullptr) {
                            delete c; PyErr_NoMemory(); return nullptr;
                        }
                        c->entries = grown;
                    }
                    c->entries[e++] = i;
                }
            }
        }
        c->offsets[ci++] = e;
    }
    c->n_offsets = ci;
    c->n_entries = e;
    if (e < cap) {  /* shrink the doubling overshoot to fit */
        long long *fit = (long long *)PyMem_Realloc(
            c->entries, sizeof(long long) * (e ? e : 1));
        if (fit != nullptr) c->entries = fit;
    }
    PyObject *capsule = PyCapsule_New(c, "accord.cintia", cintia_destroy);
    if (capsule == nullptr) delete c;
    return capsule;
}

inline Cintia *get_cintia(PyObject *capsule) {
    return (Cintia *)PyCapsule_GetPointer(capsule, "accord.cintia");
}

/* visit checkpoint-open intervals for the block of `j` (count of starts <=
 * point), then the run [cp, j), appending indices with end > point */
bool visit_stab(const Cintia *c, long long point, Py_ssize_t j,
                PyObject *out) {
    if (j == 0) return true;
    Py_ssize_t cp = ((j - 1) / c->every) * c->every;
    Py_ssize_t ci = cp / c->every;
    Py_ssize_t lo = ci > 0 ? (Py_ssize_t)c->offsets[ci - 1] : 0;
    Py_ssize_t hi = (Py_ssize_t)c->offsets[ci];
    for (Py_ssize_t e = lo; e < hi; ++e) {
        Py_ssize_t i = (Py_ssize_t)c->entries[e];
        if (c->ends[i] > point) {
            PyObject *idx = PyLong_FromSsize_t(i);
            if (idx == nullptr || PyList_Append(out, idx) < 0) {
                Py_XDECREF(idx); return false;
            }
            Py_DECREF(idx);
        }
    }
    for (Py_ssize_t i = cp; i < j; ++i) {
        if (c->ends[i] > point) {
            PyObject *idx = PyLong_FromSsize_t(i);
            if (idx == nullptr || PyList_Append(out, idx) < 0) {
                Py_XDECREF(idx); return false;
            }
            Py_DECREF(idx);
        }
    }
    return true;
}

PyObject *cintia_find(PyObject *, PyObject *args) {
    PyObject *capsule;
    long long point;
    if (!PyArg_ParseTuple(args, "OL", &capsule, &point)) return nullptr;
    Cintia *c = get_cintia(capsule);
    if (c == nullptr) return nullptr;
    PyObject *out = PyList_New(0);
    if (out == nullptr) return nullptr;
    Py_ssize_t j = upper_bound(c->starts, c->n, point);
    if (!visit_stab(c, point, j, out)) { Py_DECREF(out); return nullptr; }
    return out;
}

PyObject *cintia_overlaps(PyObject *, PyObject *args) {
    PyObject *capsule;
    long long qlo, qhi;
    if (!PyArg_ParseTuple(args, "OLL", &capsule, &qlo, &qhi)) return nullptr;
    Cintia *c = get_cintia(capsule);
    if (c == nullptr) return nullptr;
    PyObject *out = PyList_New(0);
    if (out == nullptr) return nullptr;
    Py_ssize_t j = lower_bound(c->starts, c->n, qhi);
    if (j > 0) {
        Py_ssize_t jlo = upper_bound(c->starts, c->n, qlo);
        if (!visit_stab(c, qlo, jlo, out)) { Py_DECREF(out); return nullptr; }
        for (Py_ssize_t i = jlo; i < j; ++i) {
            PyObject *idx = PyLong_FromSsize_t(i);
            if (idx == nullptr || PyList_Append(out, idx) < 0) {
                Py_XDECREF(idx); Py_DECREF(out); return nullptr;
            }
            Py_DECREF(idx);
        }
    }
    return out;
}

PyMethodDef methods[] = {
    {"linear_union", linear_union, METH_VARARGS,
     "union of two sorted unique sequences"},
    {"linear_intersection", linear_intersection, METH_VARARGS,
     "intersection of two sorted unique sequences"},
    {"linear_subtract", linear_subtract, METH_VARARGS,
     "difference of two sorted unique sequences"},
    {"binary_search", binary_search, METH_VARARGS,
     "Java-convention binary search"},
    {"linear_merge_n", linear_merge_n, METH_VARARGS,
     "k-way union of sorted unique sequences"},
    {"cintia_build", cintia_build, METH_VARARGS,
     "build checkpoint lists for the interval index"},
    {"cintia_find", cintia_find, METH_VARARGS,
     "stabbing query: indices of intervals containing a point"},
    {"cintia_overlaps", cintia_overlaps, METH_VARARGS,
     "overlap query: indices of intervals intersecting [lo, hi)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_accord_native",
    "native sorted-array kernels", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

extern "C" PyMODINIT_FUNC PyInit__accord_native(void) {
    return PyModule_Create(&moduledef);
}
