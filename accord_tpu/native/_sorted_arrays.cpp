/* Native sorted-array kernels — the framework's hottest host-side loops.
 *
 * Reference: accord/utils/SortedArrays.java:44 (linearUnion /
 * linearIntersection / linearSubtract and the binary-search family). These
 * run under every Keys/RoutingKeys/TxnId merge in the protocol engine, so
 * they get a C implementation mirroring accord_tpu/utils/sorted_arrays.py
 * exactly — including the identity-return convention of linear_union (one
 * input subsuming the other is returned as the SAME object so singleton
 * checks like KeyDeps.NONE keep working).
 *
 * Elements are arbitrary Python objects ordered via rich comparison (<),
 * exactly like the Python tier; comparison errors propagate.
 *
 * Built on first import by accord_tpu/native/__init__.py (g++ into a cached
 * shared object); everything falls back to the Python tier when no
 * toolchain is present.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

namespace {

/* a < b via rich comparison; -1 on error */
inline int lt(PyObject *a, PyObject *b) {
    return PyObject_RichCompareBool(a, b, Py_LT);
}

struct FastSeq {
    PyObject *seq = nullptr;
    PyObject **items = nullptr;
    Py_ssize_t n = 0;

    bool init(PyObject *obj) {
        seq = PySequence_Fast(obj, "expected a sequence");
        if (seq == nullptr) return false;
        items = PySequence_Fast_ITEMS(seq);
        n = PySequence_Fast_GET_SIZE(seq);
        return true;
    }
    ~FastSeq() { Py_XDECREF(seq); }
};

PyObject *linear_union(PyObject *, PyObject *args) {
    PyObject *ao, *bo;
    if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return nullptr;
    FastSeq a, b;
    if (!a.init(ao) || !b.init(bo)) return nullptr;
    if (a.n == 0) {
        if (PyList_Check(bo)) { Py_INCREF(bo); return bo; }
        return PySequence_List(bo);
    }
    if (b.n == 0) {
        if (PyList_Check(ao)) { Py_INCREF(ao); return ao; }
        return PySequence_List(ao);
    }
    PyObject *out = PyList_New(0);
    if (out == nullptr) return nullptr;
    Py_ssize_t i = 0, j = 0;
    while (i < a.n && j < b.n) {
        PyObject *x = a.items[i], *y = b.items[j];
        int xy = lt(x, y);
        if (xy < 0) goto fail;
        if (xy) {
            if (PyList_Append(out, x) < 0) goto fail;
            ++i;
        } else {
            int yx = lt(y, x);
            if (yx < 0) goto fail;
            if (yx) {
                if (PyList_Append(out, y) < 0) goto fail;
                ++j;
            } else {
                if (PyList_Append(out, x) < 0) goto fail;
                ++i; ++j;
            }
        }
    }
    for (; i < a.n; ++i)
        if (PyList_Append(out, a.items[i]) < 0) goto fail;
    for (; j < b.n; ++j)
        if (PyList_Append(out, b.items[j]) < 0) goto fail;
    return out;
fail:
    Py_DECREF(out);
    return nullptr;
}

PyObject *linear_intersection(PyObject *, PyObject *args) {
    PyObject *ao, *bo;
    if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return nullptr;
    FastSeq a, b;
    if (!a.init(ao) || !b.init(bo)) return nullptr;
    PyObject *out = PyList_New(0);
    if (out == nullptr) return nullptr;
    Py_ssize_t i = 0, j = 0;
    while (i < a.n && j < b.n) {
        PyObject *x = a.items[i], *y = b.items[j];
        int xy = lt(x, y);
        if (xy < 0) goto fail;
        if (xy) { ++i; continue; }
        int yx = lt(y, x);
        if (yx < 0) goto fail;
        if (yx) { ++j; continue; }
        if (PyList_Append(out, x) < 0) goto fail;
        ++i; ++j;
    }
    return out;
fail:
    Py_DECREF(out);
    return nullptr;
}

PyObject *linear_subtract(PyObject *, PyObject *args) {
    PyObject *ao, *bo;
    if (!PyArg_ParseTuple(args, "OO", &ao, &bo)) return nullptr;
    FastSeq a, b;
    if (!a.init(ao) || !b.init(bo)) return nullptr;
    PyObject *out = PyList_New(0);
    if (out == nullptr) return nullptr;
    Py_ssize_t i = 0, j = 0;
    while (i < a.n && j < b.n) {
        PyObject *x = a.items[i], *y = b.items[j];
        int xy = lt(x, y);
        if (xy < 0) goto fail;
        if (xy) {
            if (PyList_Append(out, x) < 0) goto fail;
            ++i; continue;
        }
        int yx = lt(y, x);
        if (yx < 0) goto fail;
        if (yx) { ++j; continue; }
        ++i; ++j;
    }
    for (; i < a.n; ++i)
        if (PyList_Append(out, a.items[i]) < 0) goto fail;
    return out;
fail:
    Py_DECREF(out);
    return nullptr;
}

/* binary_search(xs, target, lo=0, hi=None) -> match index or
 * -(insertion_point)-1, the Java convention the Python tier mirrors */
PyObject *binary_search(PyObject *, PyObject *args) {
    PyObject *xso, *target, *hio = Py_None;
    Py_ssize_t lo = 0;
    if (!PyArg_ParseTuple(args, "OO|nO", &xso, &target, &lo, &hio))
        return nullptr;
    FastSeq xs;
    if (!xs.init(xso)) return nullptr;
    Py_ssize_t hi = xs.n;
    if (hio != Py_None) {
        hi = PyNumber_AsSsize_t(hio, PyExc_OverflowError);
        if (hi == -1 && PyErr_Occurred()) return nullptr;
    }
    /* out-of-contract bounds raise exactly like the Python tier's xs[mid]
     * would — never read outside the item array */
    if (lo < 0 || hi > xs.n) {
        PyErr_SetString(PyExc_IndexError, "binary_search bounds outside sequence");
        return nullptr;
    }
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) / 2;
        PyObject *v = xs.items[mid];
        int vlt = lt(v, target);
        if (vlt < 0) return nullptr;
        if (vlt) { lo = mid + 1; continue; }
        int tlt = lt(target, v);
        if (tlt < 0) return nullptr;
        if (tlt) hi = mid;
        else return PyLong_FromSsize_t(mid);
    }
    return PyLong_FromSsize_t(-(lo + 1));
}

PyMethodDef methods[] = {
    {"linear_union", linear_union, METH_VARARGS,
     "union of two sorted unique sequences"},
    {"linear_intersection", linear_intersection, METH_VARARGS,
     "intersection of two sorted unique sequences"},
    {"linear_subtract", linear_subtract, METH_VARARGS,
     "difference of two sorted unique sequences"},
    {"binary_search", binary_search, METH_VARARGS,
     "Java-convention binary search"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_accord_native",
    "native sorted-array kernels", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

extern "C" PyMODINIT_FUNC PyInit__accord_native(void) {
    return PyModule_Create(&moduledef);
}
