/* Native binary frame codec — the TCP host's hot encode/decode path.
 *
 * Serialises the structural wire tree (accord_tpu/host/wire.py `encode`
 * output: None/bool/int/float/str/list/dict, plus the single-key
 * timestamp/key fast-path dicts) into the tagged binary format defined in
 * host/wire.py.  The contract is BYTE-IDENTICAL output with the
 * pure-Python tier (`py_pack`/`py_unpack`): tests/test_wire_roundtrip.py
 * cross-checks both directions over every registered verb, so a host on
 * either tier interoperates bit-for-bit with the other.
 *
 * Built on first import by accord_tpu/native/__init__.py (g++ into a
 * cached shared object, same lazy-build pattern as _sorted_arrays.cpp);
 * any build/load failure degrades silently to the Python tier.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

constexpr unsigned char T_NONE = 0x00, T_FALSE = 0x01, T_TRUE = 0x02,
    T_INT = 0x03, T_FLOAT = 0x04, T_STR = 0x05, T_LIST = 0x06,
    T_DICT = 0x07, T_TS = 0x08, T_TXNID = 0x09, T_BALLOT = 0x0A,
    T_KEY = 0x0B, T_RKEY = 0x0C, T_KEYS = 0x0D, T_RKEYS = 0x0E,
    T_ITUPLE = 0x0F, T_BIGINT = 0x10;

constexpr int MAX_DEPTH = 200;  /* hostile-input recursion bound */

/* ---- object-packing bindings (wire_bind) ----
 * The payload boundary: frame bodies are TREES (dict/list/scalar), but a
 * body's "payload" may be the RAW protocol message object — pack_value
 * switches to pack_object there and serialises the whole message in one
 * native pass (no intermediate encode() tree).  The Python tier mirrors
 * this byte-for-byte by packing encode(obj)'s tree. */
static PyObject *g_ts, *g_txnid, *g_ballot, *g_key, *g_rkey, *g_keys,
    *g_rkeys;
static PyObject *g_enum_base;         /* enum.Enum */
static PyObject *g_registry_provider; /* callable -> ({name: cls},
                                         {name: enum_cls}) */
static PyObject *g_registry;          /* cached classes dict */
static PyObject *g_enums;             /* cached enums dict */
static PyObject *g_slots_of;          /* callable cls -> [slot, ...] */
static PyObject *g_slots_cache;       /* dict cls -> list */
static PyObject *g_py_encode;         /* wire.encode (fallback) */
static PyObject *s_epoch, *s_hlc, *s_flags, *s_node, *s_token, *s_keys_attr,
    *s_dict_attr, *s_value_attr, *s_name_attr;
constexpr int HLC_LOW_BITS = 48;      /* timestamp.py _HLC_LOW_BITS */
constexpr uint64_t HLC_LOW_MASK = (1ULL << HLC_LOW_BITS) - 1;

struct Writer {
    std::string buf;

    void byte(unsigned char b) { buf.push_back((char)b); }
    void raw(const char *p, Py_ssize_t n) { buf.append(p, (size_t)n); }

    void varint(uint64_t v) {
        while (v >= 0x80) {
            byte((unsigned char)((v & 0x7F) | 0x80));
            v >>= 7;
        }
        byte((unsigned char)v);
    }
    void zigzag(int64_t n) {
        varint(((uint64_t)n << 1) ^ (uint64_t)(n >> 63));
    }
};

/* exact int64 value of an exact-type int, with ok=false on overflow */
inline bool as_i64(PyObject *obj, int64_t *out) {
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
    if (overflow != 0) return false;
    if (v == -1 && PyErr_Occurred()) return false;  /* propagated by caller */
    *out = (int64_t)v;
    return true;
}

/* all elements of a list are exact ints fitting int64 */
bool all_i64_list(PyObject *list) {
    Py_ssize_t n = PyList_GET_SIZE(list);
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject *x = PyList_GET_ITEM(list, i);
        if (!PyLong_CheckExact(x)) return false;
        int64_t v;
        if (!as_i64(x, &v)) { PyErr_Clear(); return false; }
    }
    return true;
}

/* single-key fast-path tag for a dict key name, 0 when none */
unsigned char tag_for_key(PyObject *key) {
    if (!PyUnicode_Check(key)) return 0;
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(key, &n);
    if (s == nullptr) { PyErr_Clear(); return 0; }
    if (n < 2 || n > 4 || s[0] != '$') return 0;
    if (n == 2) {
        switch (s[1]) {
            case 'T': return T_TS;
            case 'I': return T_TXNID;
            case 'B': return T_BALLOT;
            case 'K': return T_KEY;
            case 't': return T_ITUPLE;
        }
        return 0;
    }
    if (n == 3 && s[1] == 'R' && s[2] == 'K') return T_RKEY;
    if (n == 3 && s[1] == 'K' && s[2] == 's') return T_KEYS;
    if (n == 4 && memcmp(s + 1, "RKs", 3) == 0) return T_RKEYS;
    return 0;
}

bool pack_value(PyObject *obj, Writer &w, int depth);
bool pack_object(PyObject *obj, Writer &w, int depth);

bool pack_generic_dict(PyObject *obj, Writer &w, int depth) {
    w.byte(T_DICT);
    w.varint((uint64_t)PyDict_GET_SIZE(obj));
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
        if (!pack_value(key, w, depth + 1)) return false;
        if (!pack_value(value, w, depth + 1)) return false;
    }
    return true;
}

/* write one utf8 string value (tag + len + bytes) */
bool write_str(PyObject *s, Writer &w) {
    Py_ssize_t n;
    const char *p = PyUnicode_AsUTF8AndSize(s, &n);
    if (p == nullptr) return false;
    w.byte(T_STR);
    w.varint((uint64_t)n);
    w.raw(p, n);
    return true;
}

bool write_cstr(const char *p, Writer &w) {
    size_t n = strlen(p);
    w.byte(T_STR);
    w.varint((uint64_t)n);
    w.raw(p, (Py_ssize_t)n);
    return true;
}

/* exact unsigned-64 value of an exact-type int; ok=false on overflow/neg */
inline bool as_u64(PyObject *obj, uint64_t *out) {
    unsigned long long v = PyLong_AsUnsignedLongLong(obj);
    if (v == (unsigned long long)-1 && PyErr_Occurred()) {
        PyErr_Clear();
        return false;
    }
    *out = (uint64_t)v;
    return true;
}

bool all_u64_list(PyObject *list) {
    Py_ssize_t n = PyList_GET_SIZE(list);
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject *x = PyList_GET_ITEM(list, i);
        uint64_t v;
        if (!PyLong_CheckExact(x) || !as_u64(x, &v)) return false;
    }
    return true;
}

bool pack_int(PyObject *obj, Writer &w) {
    int64_t v;
    if (as_i64(obj, &v)) {
        w.byte(T_INT);
        w.zigzag(v);
        return true;
    }
    if (PyErr_Occurred()) return false;
    /* > int64: decimal string, same as the Python tier */
    PyObject *s = PyObject_Str(obj);
    if (s == nullptr) return false;
    Py_ssize_t n;
    const char *p = PyUnicode_AsUTF8AndSize(s, &n);
    if (p == nullptr) { Py_DECREF(s); return false; }
    w.byte(T_BIGINT);
    w.varint((uint64_t)n);
    w.raw(p, n);
    Py_DECREF(s);
    return true;
}

void pack_float(PyObject *obj, Writer &w) {
    double d = PyFloat_AS_DOUBLE(obj);
    uint64_t bits;
    memcpy(&bits, &d, 8);
    w.byte(T_FLOAT);
    for (int i = 7; i >= 0; --i)
        w.byte((unsigned char)((bits >> (8 * i)) & 0xFF));
}

bool pack_value(PyObject *obj, Writer &w, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "wire tree too deep");
        return false;
    }
    if (obj == Py_None) { w.byte(T_NONE); return true; }
    if (obj == Py_True) { w.byte(T_TRUE); return true; }
    if (obj == Py_False) { w.byte(T_FALSE); return true; }
    if (PyLong_CheckExact(obj)) return pack_int(obj, w);
    if (PyFloat_CheckExact(obj)) { pack_float(obj, w); return true; }
    if (PyUnicode_CheckExact(obj)) return write_str(obj, w);
    if (PyList_CheckExact(obj) || PyTuple_CheckExact(obj)) {
        Py_ssize_t n = PyList_CheckExact(obj) ? PyList_GET_SIZE(obj)
                                              : PyTuple_GET_SIZE(obj);
        w.byte(T_LIST);
        w.varint((uint64_t)n);
        for (Py_ssize_t i = 0; i < n; ++i) {
            PyObject *x = PyList_CheckExact(obj) ? PyList_GET_ITEM(obj, i)
                                                 : PyTuple_GET_ITEM(obj, i);
            if (!pack_value(x, w, depth + 1)) return false;
        }
        return true;
    }
    if (PyDict_CheckExact(obj)) {
        if (PyDict_GET_SIZE(obj) == 1) {
            PyObject *key, *value;
            Py_ssize_t pos = 0;
            PyDict_Next(obj, &pos, &key, &value);
            unsigned char tag = tag_for_key(key);
            if (tag == T_TS || tag == T_TXNID || tag == T_BALLOT) {
                /* timestamp packs are non-negative bit-packs whose lsb
                 * can exceed int64: UNSIGNED varints */
                if (PyList_CheckExact(value) && PyList_GET_SIZE(value) == 3
                        && all_u64_list(value)) {
                    w.byte(tag);
                    for (Py_ssize_t i = 0; i < 3; ++i) {
                        uint64_t v;
                        as_u64(PyList_GET_ITEM(value, i), &v);
                        w.varint(v);
                    }
                    return true;
                }
            } else if (tag == T_KEY || tag == T_RKEY) {
                int64_t v;
                if (PyLong_CheckExact(value) && as_i64(value, &v)) {
                    w.byte(tag);
                    w.zigzag(v);
                    return true;
                }
                if (PyErr_Occurred()) PyErr_Clear();
            } else if (tag != 0) {             /* $Ks / $RKs / $t */
                if (PyList_CheckExact(value) && all_i64_list(value)) {
                    Py_ssize_t n = PyList_GET_SIZE(value);
                    w.byte(tag);
                    w.varint((uint64_t)n);
                    for (Py_ssize_t i = 0; i < n; ++i) {
                        int64_t v;
                        as_i64(PyList_GET_ITEM(value, i), &v);
                        w.zigzag(v);
                    }
                    return true;
                }
            }
        }
        return pack_generic_dict(obj, w, depth);
    }
    /* not a tree node: the payload boundary — one-pass raw-object pack */
    return pack_object(obj, w, depth);
}

/* ---------------------------------------------------- raw object pack -- */

bool fetch_registry() {
    if (g_registry_provider == nullptr) return false;
    PyObject *pair = PyObject_CallNoArgs(g_registry_provider);
    if (pair == nullptr) return false;
    if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
        Py_DECREF(pair);
        PyErr_SetString(PyExc_TypeError,
                        "registry provider must return (classes, enums)");
        return false;
    }
    g_registry = PyTuple_GET_ITEM(pair, 0);
    g_enums = PyTuple_GET_ITEM(pair, 1);
    Py_INCREF(g_registry);
    Py_INCREF(g_enums);
    Py_DECREF(pair);
    return true;
}

bool fallback_py(PyObject *obj, Writer &w, int depth) {
    /* semantics of last resort: the Python structural walk builds the
     * tree (raising TypeError for unregistered types exactly like the
     * Python tier), and the tree packs as usual */
    if (g_py_encode == nullptr) {
        PyErr_Format(PyExc_TypeError, "binary wire codec cannot pack %s",
                     Py_TYPE(obj)->tp_name);
        return false;
    }
    PyObject *tree = PyObject_CallOneArg(g_py_encode, obj);
    if (tree == nullptr) return false;
    bool ok = pack_value(tree, w, depth);
    Py_DECREF(tree);
    return ok;
}

bool attr_u64(PyObject *obj, PyObject *name, uint64_t *out) {
    PyObject *v = PyObject_GetAttr(obj, name);
    if (v == nullptr) { PyErr_Clear(); return false; }
    bool ok = PyLong_CheckExact(v) && as_u64(v, out);
    Py_DECREF(v);
    return ok;
}

bool attr_i64(PyObject *obj, PyObject *name, int64_t *out) {
    PyObject *v = PyObject_GetAttr(obj, name);
    if (v == nullptr) { PyErr_Clear(); return false; }
    bool ok = false;
    if (PyLong_CheckExact(v)) {
        ok = as_i64(v, out);
        if (!ok && PyErr_Occurred()) PyErr_Clear();
    }
    Py_DECREF(v);
    return ok;
}

bool pack_object(PyObject *obj, Writer &w, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "wire tree too deep");
        return false;
    }
    if (obj == Py_None) { w.byte(T_NONE); return true; }
    if (obj == Py_True) { w.byte(T_TRUE); return true; }
    if (obj == Py_False) { w.byte(T_FALSE); return true; }
    if (PyLong_CheckExact(obj)) return pack_int(obj, w);
    if (PyFloat_CheckExact(obj)) { pack_float(obj, w); return true; }
    if (PyUnicode_CheckExact(obj)) return write_str(obj, w);
    PyObject *t = (PyObject *)Py_TYPE(obj);
    if (t == g_ts || t == g_txnid || t == g_ballot) {
        uint64_t epoch, hlc, flags, node;
        if (attr_u64(obj, s_epoch, &epoch) && epoch <= HLC_LOW_MASK
                && attr_u64(obj, s_hlc, &hlc)
                && attr_u64(obj, s_flags, &flags)
                && attr_u64(obj, s_node, &node)) {
            /* mirror Timestamp.pack() exactly (timestamp.py msb/lsb) */
            uint64_t msb = (epoch << 16) | ((hlc >> HLC_LOW_BITS) & 0xFFFF);
            uint64_t lsb = ((hlc & HLC_LOW_MASK) << 16) | (flags & 0xFFFF);
            w.byte(t == g_ts ? T_TS : (t == g_txnid ? T_TXNID : T_BALLOT));
            w.varint(msb);
            w.varint(lsb);
            w.varint(node);
            return true;
        }
        return fallback_py(obj, w, depth);
    }
    if (t == g_key || t == g_rkey) {
        int64_t tok;
        if (attr_i64(obj, s_token, &tok)) {
            w.byte(t == g_key ? T_KEY : T_RKEY);
            w.zigzag(tok);
            return true;
        }
        return fallback_py(obj, w, depth);
    }
    if (t == g_keys || t == g_rkeys) {
        PyObject *elems = PyObject_GetAttr(obj, s_keys_attr);
        if (elems != nullptr && PyTuple_CheckExact(elems)) {
            PyObject *want = (t == g_keys) ? g_key : g_rkey;
            Py_ssize_t n = PyTuple_GET_SIZE(elems);
            Writer tokens;
            bool ok = true;
            for (Py_ssize_t i = 0; i < n && ok; ++i) {
                PyObject *k = PyTuple_GET_ITEM(elems, i);
                int64_t tok;
                ok = ((PyObject *)Py_TYPE(k) == want)
                     && attr_i64(k, s_token, &tok);
                if (ok) tokens.zigzag(tok);
            }
            Py_DECREF(elems);
            if (ok) {
                w.byte(t == g_keys ? T_KEYS : T_RKEYS);
                w.varint((uint64_t)n);
                w.buf.append(tokens.buf);
                return true;
            }
        } else {
            Py_XDECREF(elems);
            PyErr_Clear();
        }
        return fallback_py(obj, w, depth);
    }
    if (PyList_CheckExact(obj)) {
        Py_ssize_t n = PyList_GET_SIZE(obj);
        w.byte(T_LIST);
        w.varint((uint64_t)n);
        for (Py_ssize_t i = 0; i < n; ++i)
            if (!pack_object(PyList_GET_ITEM(obj, i), w, depth + 1))
                return false;
        return true;
    }
    if (PyTuple_CheckExact(obj)) {
        /* object-context tuples are {"$t": ...}: int-only fast tag, else
         * a generic single-key dict around the element list */
        Py_ssize_t n = PyTuple_GET_SIZE(obj);
        bool ints = true;
        for (Py_ssize_t i = 0; i < n && ints; ++i) {
            PyObject *x = PyTuple_GET_ITEM(obj, i);
            int64_t v;
            ints = PyLong_CheckExact(x) && as_i64(x, &v);
            if (!ints && PyErr_Occurred()) PyErr_Clear();
        }
        if (ints) {
            w.byte(T_ITUPLE);
            w.varint((uint64_t)n);
            for (Py_ssize_t i = 0; i < n; ++i) {
                int64_t v;
                as_i64(PyTuple_GET_ITEM(obj, i), &v);
                w.zigzag(v);
            }
            return true;
        }
        w.byte(T_DICT);
        w.varint(1);
        write_cstr("$t", w);
        w.byte(T_LIST);
        w.varint((uint64_t)n);
        for (Py_ssize_t i = 0; i < n; ++i)
            if (!pack_object(PyTuple_GET_ITEM(obj, i), w, depth + 1))
                return false;
        return true;
    }
    if (PyDict_CheckExact(obj)) {
        /* a DATA dict at object level: {"$d": [[k, v], ...]} */
        w.byte(T_DICT);
        w.varint(1);
        write_cstr("$d", w);
        w.byte(T_LIST);
        w.varint((uint64_t)PyDict_GET_SIZE(obj));
        PyObject *key, *value;
        Py_ssize_t pos = 0;
        while (PyDict_Next(obj, &pos, &key, &value)) {
            w.byte(T_LIST);
            w.varint(2);
            if (!pack_object(key, w, depth + 1)) return false;
            if (!pack_object(value, w, depth + 1)) return false;
        }
        return true;
    }
    if (PySet_Check(obj) || PyFrozenSet_Check(obj)) {
        if (PySet_CheckExact(obj) || PyFrozenSet_CheckExact(obj)) {
            w.byte(T_DICT);
            w.varint(1);
            write_cstr("$s", w);
            w.byte(T_LIST);
            w.varint((uint64_t)PySet_GET_SIZE(obj));
            PyObject *it = PyObject_GetIter(obj);
            if (it == nullptr) return false;
            PyObject *x;
            while ((x = PyIter_Next(it)) != nullptr) {
                bool ok = pack_object(x, w, depth + 1);
                Py_DECREF(x);
                if (!ok) { Py_DECREF(it); return false; }
            }
            Py_DECREF(it);
            return !PyErr_Occurred();
        }
        return fallback_py(obj, w, depth);
    }
    if (g_enum_base != nullptr) {
        int is_enum = PyObject_IsInstance(obj, g_enum_base);
        if (is_enum < 0) return false;
        if (is_enum) {
            PyObject *name = PyObject_GetAttr(t, s_name_attr);
            PyObject *value = PyObject_GetAttr(obj, s_value_attr);
            if (name == nullptr || value == nullptr) {
                Py_XDECREF(name);
                Py_XDECREF(value);
                return false;
            }
            w.byte(T_DICT);
            w.varint(2);
            bool ok = write_cstr("$e", w) && write_str(name, w)
                      && write_cstr("v", w)
                      && pack_object(value, w, depth + 1);
            Py_DECREF(name);
            Py_DECREF(value);
            return ok;
        }
    }
    if (PyExceptionInstance_Check(obj)) {
        PyObject *name = PyObject_GetAttr(t, s_name_attr);
        PyObject *msg = PyObject_Str(obj);
        if (name == nullptr || msg == nullptr) {
            Py_XDECREF(name);
            Py_XDECREF(msg);
            return false;
        }
        w.byte(T_DICT);
        w.varint(2);
        bool ok = write_cstr("$x", w) && write_str(name, w)
                  && write_cstr("msg", w) && write_str(msg, w);
        Py_DECREF(name);
        Py_DECREF(msg);
        return ok;
    }
    /* registered protocol class: {"$c": name, "f": {field: ...}} */
    if (g_registry == nullptr && !fetch_registry()) return false;
    if (g_registry != nullptr && g_slots_of != nullptr) {
        PyObject *name = PyObject_GetAttr(t, s_name_attr);
        if (name == nullptr) { PyErr_Clear(); return fallback_py(obj, w, depth); }
        PyObject *cls = PyDict_GetItemWithError(g_registry, name);
        if (cls != t) {  /* unregistered or shadowed: Python semantics */
            Py_DECREF(name);
            if (PyErr_Occurred()) return false;
            return fallback_py(obj, w, depth);
        }
        PyObject *slots = PyDict_GetItemWithError(g_slots_cache, t);
        if (slots == nullptr) {
            if (PyErr_Occurred()) { Py_DECREF(name); return false; }
            slots = PyObject_CallOneArg(g_slots_of, t);
            if (slots == nullptr
                    || PyDict_SetItem(g_slots_cache, t, slots) < 0) {
                Py_XDECREF(slots);
                Py_DECREF(name);
                return false;
            }
            Py_DECREF(slots);  /* cache holds it; borrow below */
            slots = PyDict_GetItemWithError(g_slots_cache, t);
        }
        PyObject *fields = PyDict_New();
        if (fields == nullptr) { Py_DECREF(name); return false; }
        Py_ssize_t ns = PySequence_Fast_GET_SIZE(slots);
        PyObject **slot_items = PySequence_Fast_ITEMS(slots);
        for (Py_ssize_t i = 0; i < ns; ++i) {
            PyObject *v = PyObject_GetAttr(obj, slot_items[i]);
            if (v == nullptr) { PyErr_Clear(); continue; }
            int rc = PyDict_SetItem(fields, slot_items[i], v);
            Py_DECREF(v);
            if (rc < 0) { Py_DECREF(fields); Py_DECREF(name); return false; }
        }
        PyObject *d = PyObject_GetAttr(obj, s_dict_attr);
        if (d == nullptr) {
            PyErr_Clear();
        } else {
            if (PyDict_CheckExact(d)) {
                PyObject *key, *value;
                Py_ssize_t pos = 0;
                while (PyDict_Next(d, &pos, &key, &value)) {
                    if (PyDict_SetItem(fields, key, value) < 0) {
                        Py_DECREF(d); Py_DECREF(fields); Py_DECREF(name);
                        return false;
                    }
                }
            }
            Py_DECREF(d);
        }
        w.byte(T_DICT);
        w.varint(2);
        bool ok = write_cstr("$c", w) && write_str(name, w)
                  && write_cstr("f", w);
        if (ok) {
            w.byte(T_DICT);
            w.varint((uint64_t)PyDict_GET_SIZE(fields));
            PyObject *key, *value;
            Py_ssize_t pos = 0;
            while (ok && PyDict_Next(fields, &pos, &key, &value)) {
                ok = pack_value(key, w, depth + 1)
                     && pack_object(value, w, depth + 1);
            }
        }
        Py_DECREF(fields);
        Py_DECREF(name);
        return ok;
    }
    return fallback_py(obj, w, depth);
}

PyObject *wire_pack(PyObject *, PyObject *args) {
    PyObject *obj;
    if (!PyArg_ParseTuple(args, "O", &obj)) return nullptr;
    Writer w;
    w.buf.reserve(256);
    if (!pack_value(obj, w, 0)) return nullptr;
    return PyBytes_FromStringAndSize(w.buf.data(),
                                     (Py_ssize_t)w.buf.size());
}

/* ------------------------------------------------------------- unpack -- */

struct Reader {
    const unsigned char *data;
    Py_ssize_t n, pos = 0;

    bool need(Py_ssize_t k) {
        if (pos + k > n) {
            PyErr_SetString(PyExc_ValueError, "truncated binary frame");
            return false;
        }
        return true;
    }
    bool byte(unsigned char *out) {
        if (!need(1)) return false;
        *out = data[pos++];
        return true;
    }
    bool varint(uint64_t *out) {
        uint64_t v = 0;
        int shift = 0;
        unsigned char b;
        do {
            if (shift > 70) {
                PyErr_SetString(PyExc_ValueError, "varint too long");
                return false;
            }
            if (!byte(&b)) return false;
            v |= (uint64_t)(b & 0x7F) << shift;
            shift += 7;
        } while (b & 0x80);
        *out = v;
        return true;
    }
    bool zigzag(int64_t *out) {
        uint64_t u;
        if (!varint(&u)) return false;
        *out = (int64_t)((u >> 1) ^ (~(u & 1) + 1));
        return true;
    }
};

/* the single-key dict {"<name>": value}, stealing `value` */
PyObject *dict1(const char *name, PyObject *value) {
    if (value == nullptr) return nullptr;
    PyObject *d = PyDict_New();
    if (d == nullptr || PyDict_SetItemString(d, name, value) < 0) {
        Py_XDECREF(d);
        Py_DECREF(value);
        return nullptr;
    }
    Py_DECREF(value);
    return d;
}

const char *key_for_tag(unsigned char tag) {
    switch (tag) {
        case T_TS: return "$T";
        case T_TXNID: return "$I";
        case T_BALLOT: return "$B";
        case T_KEY: return "$K";
        case T_RKEY: return "$RK";
        case T_KEYS: return "$Ks";
        case T_RKEYS: return "$RKs";
        case T_ITUPLE: return "$t";
    }
    return nullptr;
}

PyObject *unpack_value(Reader &r, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "wire tree too deep");
        return nullptr;
    }
    unsigned char tag;
    if (!r.byte(&tag)) return nullptr;
    switch (tag) {
        case T_NONE: Py_RETURN_NONE;
        case T_TRUE: Py_RETURN_TRUE;
        case T_FALSE: Py_RETURN_FALSE;
        case T_INT: {
            int64_t v;
            if (!r.zigzag(&v)) return nullptr;
            return PyLong_FromLongLong((long long)v);
        }
        case T_FLOAT: {
            if (!r.need(8)) return nullptr;
            uint64_t bits = 0;
            for (int i = 0; i < 8; ++i)
                bits = (bits << 8) | r.data[r.pos++];
            double d;
            memcpy(&d, &bits, 8);
            return PyFloat_FromDouble(d);
        }
        case T_STR: {
            uint64_t n;
            if (!r.varint(&n) || !r.need((Py_ssize_t)n)) return nullptr;
            PyObject *s = PyUnicode_DecodeUTF8(
                (const char *)r.data + r.pos, (Py_ssize_t)n, nullptr);
            r.pos += (Py_ssize_t)n;
            return s;
        }
        case T_LIST: {
            uint64_t n;
            if (!r.varint(&n)) return nullptr;
            if ((Py_ssize_t)n > r.n - r.pos) {  /* >=1 byte per element */
                PyErr_SetString(PyExc_ValueError, "truncated binary frame");
                return nullptr;
            }
            PyObject *list = PyList_New((Py_ssize_t)n);
            if (list == nullptr) return nullptr;
            for (Py_ssize_t i = 0; i < (Py_ssize_t)n; ++i) {
                PyObject *x = unpack_value(r, depth + 1);
                if (x == nullptr) { Py_DECREF(list); return nullptr; }
                PyList_SET_ITEM(list, i, x);
            }
            return list;
        }
        case T_DICT: {
            uint64_t n;
            if (!r.varint(&n)) return nullptr;
            if ((Py_ssize_t)n > r.n - r.pos) {
                PyErr_SetString(PyExc_ValueError, "truncated binary frame");
                return nullptr;
            }
            PyObject *d = PyDict_New();
            if (d == nullptr) return nullptr;
            for (uint64_t i = 0; i < n; ++i) {
                PyObject *k = unpack_value(r, depth + 1);
                if (k == nullptr) { Py_DECREF(d); return nullptr; }
                PyObject *v = unpack_value(r, depth + 1);
                if (v == nullptr) { Py_DECREF(k); Py_DECREF(d);
                                    return nullptr; }
                int rc = PyDict_SetItem(d, k, v);
                Py_DECREF(k);
                Py_DECREF(v);
                if (rc < 0) { Py_DECREF(d); return nullptr; }
            }
            return d;
        }
        case T_TS: case T_TXNID: case T_BALLOT: {
            PyObject *list = PyList_New(3);
            if (list == nullptr) return nullptr;
            for (int i = 0; i < 3; ++i) {
                uint64_t v;           /* timestamp packs: UNSIGNED varints */
                if (!r.varint(&v)) { Py_DECREF(list); return nullptr; }
                PyObject *x = PyLong_FromUnsignedLongLong(v);
                if (x == nullptr) { Py_DECREF(list); return nullptr; }
                PyList_SET_ITEM(list, i, x);
            }
            return dict1(key_for_tag(tag), list);
        }
        case T_KEY: case T_RKEY: {
            int64_t v;
            if (!r.zigzag(&v)) return nullptr;
            return dict1(key_for_tag(tag),
                         PyLong_FromLongLong((long long)v));
        }
        case T_KEYS: case T_RKEYS: case T_ITUPLE: {
            uint64_t n;
            if (!r.varint(&n)) return nullptr;
            if ((Py_ssize_t)n > r.n - r.pos) {
                PyErr_SetString(PyExc_ValueError, "truncated binary frame");
                return nullptr;
            }
            PyObject *list = PyList_New((Py_ssize_t)n);
            if (list == nullptr) return nullptr;
            for (Py_ssize_t i = 0; i < (Py_ssize_t)n; ++i) {
                int64_t v;
                if (!r.zigzag(&v)) { Py_DECREF(list); return nullptr; }
                PyObject *x = PyLong_FromLongLong((long long)v);
                if (x == nullptr) { Py_DECREF(list); return nullptr; }
                PyList_SET_ITEM(list, i, x);
            }
            return dict1(key_for_tag(tag), list);
        }
        case T_BIGINT: {
            uint64_t n;
            if (!r.varint(&n) || !r.need((Py_ssize_t)n)) return nullptr;
            std::string s((const char *)r.data + r.pos, (size_t)n);
            r.pos += (Py_ssize_t)n;
            return PyLong_FromString(s.c_str(), nullptr, 10);
        }
    }
    PyErr_Format(PyExc_ValueError, "unknown binary wire tag 0x%02x",
                 (int)tag);
    return nullptr;
}

/* ---------------------------------------------- one-pass object decode --
 * bytes -> decoded frame: plain dicts stay dicts (frame/body structure),
 * tagged dicts and the primitive tags become PROTOCOL OBJECTS — the
 * native fusion of unpack_frame + decode_message the TCP host's ingress
 * runs per frame. */

static PyObject *s_unpack_attr, *s_new_attr, *s_presorted_kw;

PyObject *unpack_obj(Reader &r, int depth);

PyObject *unpack_obj_list(Reader &r, int depth, Py_ssize_t n) {
    PyObject *list = PyList_New(n);
    if (list == nullptr) return nullptr;
    for (Py_ssize_t i = 0; i < n; ++i) {
        PyObject *x = unpack_obj(r, depth);
        if (x == nullptr) { Py_DECREF(list); return nullptr; }
        PyList_SET_ITEM(list, i, x);
    }
    return list;
}

/* expect a T_LIST header and return its decoded elements */
PyObject *expect_list(Reader &r, int depth) {
    unsigned char tag;
    if (!r.byte(&tag)) return nullptr;
    if (tag != T_LIST) {
        PyErr_SetString(PyExc_ValueError, "malformed tagged container");
        return nullptr;
    }
    uint64_t n;
    if (!r.varint(&n)) return nullptr;
    if ((Py_ssize_t)n > r.n - r.pos) {
        PyErr_SetString(PyExc_ValueError, "truncated binary frame");
        return nullptr;
    }
    return unpack_obj_list(r, depth + 1, (Py_ssize_t)n);
}

/* read the next value and require a str (tagged-dict keys) */
PyObject *expect_str(Reader &r, int depth) {
    PyObject *k = unpack_obj(r, depth);
    if (k == nullptr) return nullptr;
    if (!PyUnicode_CheckExact(k)) {
        Py_DECREF(k);
        PyErr_SetString(PyExc_ValueError, "malformed tagged dict");
        return nullptr;
    }
    return k;
}

PyObject *call_ts_unpack(PyObject *cls, Reader &r) {
    uint64_t m, l, n;
    if (!r.varint(&m) || !r.varint(&l) || !r.varint(&n)) return nullptr;
    PyObject *pm = PyLong_FromUnsignedLongLong(m);
    PyObject *pl = PyLong_FromUnsignedLongLong(l);
    PyObject *pn = PyLong_FromUnsignedLongLong(n);
    PyObject *out = nullptr;
    if (pm != nullptr && pl != nullptr && pn != nullptr)
        out = PyObject_CallMethodObjArgs(cls, s_unpack_attr, pm, pl, pn,
                                         nullptr);
    Py_XDECREF(pm);
    Py_XDECREF(pl);
    Py_XDECREF(pn);
    return out;
}

PyObject *make_keys(PyObject *key_cls, PyObject *keys_cls, Reader &r) {
    uint64_t n;
    if (!r.varint(&n)) return nullptr;
    if ((Py_ssize_t)n > r.n - r.pos) {
        PyErr_SetString(PyExc_ValueError, "truncated binary frame");
        return nullptr;
    }
    PyObject *elems = PyList_New((Py_ssize_t)n);
    if (elems == nullptr) return nullptr;
    int64_t prev = 0;
    bool sorted_ok = true;  /* strictly ascending, like the Python tier */
    for (Py_ssize_t i = 0; i < (Py_ssize_t)n; ++i) {
        int64_t tok;
        if (!r.zigzag(&tok)) { Py_DECREF(elems); return nullptr; }
        if (i > 0 && tok <= prev) sorted_ok = false;
        prev = tok;
        PyObject *ptok = PyLong_FromLongLong((long long)tok);
        PyObject *k = ptok ? PyObject_CallOneArg(key_cls, ptok) : nullptr;
        Py_XDECREF(ptok);
        if (k == nullptr) { Py_DECREF(elems); return nullptr; }
        PyList_SET_ITEM(elems, i, k);
    }
    PyObject *kwargs = PyDict_New();
    PyObject *argt = PyTuple_Pack(1, elems);
    Py_DECREF(elems);
    PyObject *out = nullptr;
    if (kwargs != nullptr && argt != nullptr
            && PyDict_SetItem(kwargs, s_presorted_kw,
                              sorted_ok ? Py_True : Py_False) == 0)
        out = PyObject_Call(keys_cls, argt, kwargs);
    Py_XDECREF(kwargs);
    Py_XDECREF(argt);
    return out;
}

/* tagged-dict object semantics; consumes the remaining pairs after the
 * first key (already read).  Returns the decoded object. */
PyObject *unpack_tagged_dict(Reader &r, int depth, uint64_t count,
                             PyObject *first_key) {
    const char *k = PyUnicode_AsUTF8(first_key);
    if (k == nullptr) return nullptr;
    if (count == 1 && strcmp(k, "$d") == 0) {
        PyObject *pairs = expect_list(r, depth);
        if (pairs == nullptr) return nullptr;
        PyObject *d = PyDict_New();
        if (d == nullptr) { Py_DECREF(pairs); return nullptr; }
        for (Py_ssize_t i = 0; i < PyList_GET_SIZE(pairs); ++i) {
            PyObject *kv = PyList_GET_ITEM(pairs, i);
            if (!PyList_CheckExact(kv) || PyList_GET_SIZE(kv) != 2) {
                PyErr_SetString(PyExc_ValueError, "malformed $d pair");
                Py_DECREF(pairs); Py_DECREF(d);
                return nullptr;
            }
            if (PyDict_SetItem(d, PyList_GET_ITEM(kv, 0),
                               PyList_GET_ITEM(kv, 1)) < 0) {
                Py_DECREF(pairs); Py_DECREF(d);
                return nullptr;
            }
        }
        Py_DECREF(pairs);
        return d;
    }
    if (count == 1 && strcmp(k, "$s") == 0) {
        PyObject *items = expect_list(r, depth);
        if (items == nullptr) return nullptr;
        PyObject *out = PyFrozenSet_New(items);
        Py_DECREF(items);
        return out;
    }
    if (count == 1 && strcmp(k, "$t") == 0) {
        PyObject *items = expect_list(r, depth);
        if (items == nullptr) return nullptr;
        PyObject *out = PyList_AsTuple(items);
        Py_DECREF(items);
        return out;
    }
    if (count == 2 && strcmp(k, "$e") == 0) {
        PyObject *name = expect_str(r, depth);  /* enum type name */
        if (name == nullptr) return nullptr;
        PyObject *vkey = expect_str(r, depth);  /* "v" */
        if (vkey == nullptr) { Py_DECREF(name); return nullptr; }
        Py_DECREF(vkey);
        PyObject *value = unpack_obj(r, depth);
        if (value == nullptr) { Py_DECREF(name); return nullptr; }
        if (g_enums == nullptr && !fetch_registry()) {
            Py_DECREF(name); Py_DECREF(value);
            return nullptr;
        }
        PyObject *cls = PyDict_GetItemWithError(g_enums, name);
        if (cls == nullptr) {
            if (!PyErr_Occurred())
                PyErr_Format(PyExc_KeyError, "unknown wire enum %U", name);
            Py_DECREF(name); Py_DECREF(value);
            return nullptr;
        }
        Py_DECREF(name);
        PyObject *out = PyObject_CallOneArg(cls, value);
        Py_DECREF(value);
        return out;
    }
    if (count == 2 && strcmp(k, "$x") == 0) {
        PyObject *name = expect_str(r, depth);
        if (name == nullptr) return nullptr;
        PyObject *mkey = expect_str(r, depth);  /* "msg" */
        if (mkey == nullptr) { Py_DECREF(name); return nullptr; }
        Py_DECREF(mkey);
        PyObject *msg = unpack_obj(r, depth);
        if (msg == nullptr) { Py_DECREF(name); return nullptr; }
        if (g_registry == nullptr && !fetch_registry()) {
            Py_DECREF(name); Py_DECREF(msg);
            return nullptr;
        }
        PyObject *cls = PyDict_GetItemWithError(g_registry, name);
        PyObject *out = nullptr;
        if (cls != nullptr
                && PyObject_IsSubclass(cls, PyExc_BaseException) == 1) {
            out = PyObject_CallOneArg(cls, msg);
        } else {
            PyErr_Clear();
            out = PyObject_CallFunction(PyExc_RuntimeError, "N",
                                        PyUnicode_FromFormat("%U: %U",
                                                             name, msg));
        }
        Py_DECREF(name);
        Py_DECREF(msg);
        return out;
    }
    if (count == 2 && strcmp(k, "$c") == 0) {
        PyObject *name = expect_str(r, depth);
        if (name == nullptr) return nullptr;
        PyObject *fkey = expect_str(r, depth);  /* "f" */
        if (fkey == nullptr) { Py_DECREF(name); return nullptr; }
        Py_DECREF(fkey);
        if (g_registry == nullptr && !fetch_registry()) {
            Py_DECREF(name);
            return nullptr;
        }
        PyObject *cls = PyDict_GetItemWithError(g_registry, name);
        if (cls == nullptr) {
            if (!PyErr_Occurred())
                PyErr_Format(PyExc_TypeError, "unregistered wire type: %U",
                             name);
            Py_DECREF(name);
            return nullptr;
        }
        Py_DECREF(name);
        unsigned char tag;
        uint64_t nf;
        if (!r.byte(&tag) || tag != T_DICT || !r.varint(&nf)) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_ValueError, "malformed $c fields");
            return nullptr;
        }
        PyObject *obj = PyObject_CallMethodObjArgs(cls, s_new_attr, cls,
                                                   nullptr);
        if (obj == nullptr) return nullptr;
        for (uint64_t i = 0; i < nf; ++i) {
            PyObject *fname = unpack_obj(r, depth);
            if (fname == nullptr) { Py_DECREF(obj); return nullptr; }
            PyObject *fval = unpack_obj(r, depth);
            if (fval == nullptr) {
                Py_DECREF(fname); Py_DECREF(obj);
                return nullptr;
            }
            /* object.__setattr__ exactly like the Python tier */
            int rc = PyObject_GenericSetAttr(obj, fname, fval);
            Py_DECREF(fname);
            Py_DECREF(fval);
            if (rc < 0) { Py_DECREF(obj); return nullptr; }
        }
        return obj;
    }
    /* plain dict that merely starts with a $-named key: fall through to
     * dict semantics (no such frame exists today; belt only) */
    PyObject *d = PyDict_New();
    if (d == nullptr) return nullptr;
    PyObject *v = unpack_obj(r, depth);
    if (v == nullptr || PyDict_SetItem(d, first_key, v) < 0) {
        Py_XDECREF(v); Py_DECREF(d);
        return nullptr;
    }
    Py_DECREF(v);
    for (uint64_t i = 1; i < count; ++i) {
        PyObject *dk = unpack_obj(r, depth);
        PyObject *dv = dk ? unpack_obj(r, depth) : nullptr;
        int rc = (dk && dv) ? PyDict_SetItem(d, dk, dv) : -1;
        Py_XDECREF(dk);
        Py_XDECREF(dv);
        if (rc < 0) { Py_DECREF(d); return nullptr; }
    }
    return d;
}

PyObject *unpack_obj(Reader &r, int depth) {
    if (depth > MAX_DEPTH) {
        PyErr_SetString(PyExc_ValueError, "wire tree too deep");
        return nullptr;
    }
    unsigned char tag;
    if (!r.byte(&tag)) return nullptr;
    switch (tag) {
        case T_NONE: Py_RETURN_NONE;
        case T_TRUE: Py_RETURN_TRUE;
        case T_FALSE: Py_RETURN_FALSE;
        case T_INT: {
            int64_t v;
            if (!r.zigzag(&v)) return nullptr;
            return PyLong_FromLongLong((long long)v);
        }
        case T_FLOAT: {
            if (!r.need(8)) return nullptr;
            uint64_t bits = 0;
            for (int i = 0; i < 8; ++i)
                bits = (bits << 8) | r.data[r.pos++];
            double d;
            memcpy(&d, &bits, 8);
            return PyFloat_FromDouble(d);
        }
        case T_STR: {
            uint64_t n;
            if (!r.varint(&n) || !r.need((Py_ssize_t)n)) return nullptr;
            PyObject *s = PyUnicode_DecodeUTF8(
                (const char *)r.data + r.pos, (Py_ssize_t)n, nullptr);
            r.pos += (Py_ssize_t)n;
            return s;
        }
        case T_BIGINT: {
            uint64_t n;
            if (!r.varint(&n) || !r.need((Py_ssize_t)n)) return nullptr;
            std::string s((const char *)r.data + r.pos, (size_t)n);
            r.pos += (Py_ssize_t)n;
            return PyLong_FromString(s.c_str(), nullptr, 10);
        }
        case T_LIST: {
            uint64_t n;
            if (!r.varint(&n)) return nullptr;
            if ((Py_ssize_t)n > r.n - r.pos) {
                PyErr_SetString(PyExc_ValueError, "truncated binary frame");
                return nullptr;
            }
            return unpack_obj_list(r, depth + 1, (Py_ssize_t)n);
        }
        case T_DICT: {
            uint64_t n;
            if (!r.varint(&n)) return nullptr;
            if ((Py_ssize_t)n > r.n - r.pos) {
                PyErr_SetString(PyExc_ValueError, "truncated binary frame");
                return nullptr;
            }
            if (n == 0) return PyDict_New();
            PyObject *first = unpack_obj(r, depth + 1);
            if (first == nullptr) return nullptr;
            if (PyUnicode_CheckExact(first)) {
                PyObject *out = unpack_tagged_dict(r, depth + 1, n, first);
                Py_DECREF(first);
                return out;
            }
            /* non-str first key: plain dict */
            PyObject *d = PyDict_New();
            PyObject *v = d ? unpack_obj(r, depth + 1) : nullptr;
            int rc = (d && v) ? PyDict_SetItem(d, first, v) : -1;
            Py_DECREF(first);
            Py_XDECREF(v);
            if (rc < 0) { Py_XDECREF(d); return nullptr; }
            for (uint64_t i = 1; i < n; ++i) {
                PyObject *dk = unpack_obj(r, depth + 1);
                PyObject *dv = dk ? unpack_obj(r, depth + 1) : nullptr;
                rc = (dk && dv) ? PyDict_SetItem(d, dk, dv) : -1;
                Py_XDECREF(dk);
                Py_XDECREF(dv);
                if (rc < 0) { Py_DECREF(d); return nullptr; }
            }
            return d;
        }
        case T_TS: return call_ts_unpack(g_ts, r);
        case T_TXNID: return call_ts_unpack(g_txnid, r);
        case T_BALLOT: return call_ts_unpack(g_ballot, r);
        case T_KEY: case T_RKEY: {
            int64_t v;
            if (!r.zigzag(&v)) return nullptr;
            PyObject *tok = PyLong_FromLongLong((long long)v);
            if (tok == nullptr) return nullptr;
            PyObject *out = PyObject_CallOneArg(
                tag == T_KEY ? g_key : g_rkey, tok);
            Py_DECREF(tok);
            return out;
        }
        case T_KEYS:
            return make_keys(g_key, g_keys, r);
        case T_RKEYS:
            return make_keys(g_rkey, g_rkeys, r);
        case T_ITUPLE: {
            uint64_t n;
            if (!r.varint(&n)) return nullptr;
            if ((Py_ssize_t)n > r.n - r.pos) {
                PyErr_SetString(PyExc_ValueError, "truncated binary frame");
                return nullptr;
            }
            PyObject *t = PyTuple_New((Py_ssize_t)n);
            if (t == nullptr) return nullptr;
            for (Py_ssize_t i = 0; i < (Py_ssize_t)n; ++i) {
                int64_t v;
                if (!r.zigzag(&v)) { Py_DECREF(t); return nullptr; }
                PyObject *x = PyLong_FromLongLong((long long)v);
                if (x == nullptr) { Py_DECREF(t); return nullptr; }
                PyTuple_SET_ITEM(t, i, x);
            }
            return t;
        }
    }
    PyErr_Format(PyExc_ValueError, "unknown binary wire tag 0x%02x",
                 (int)tag);
    return nullptr;
}

PyObject *wire_unpack_obj(PyObject *, PyObject *args) {
    Py_buffer view;
    if (!PyArg_ParseTuple(args, "y*", &view)) return nullptr;
    if (g_ts == nullptr) {
        PyBuffer_Release(&view);
        PyErr_SetString(PyExc_RuntimeError,
                        "wire_unpack_obj requires wire_bind");
        return nullptr;
    }
    Reader r{(const unsigned char *)view.buf, view.len};
    PyObject *out = unpack_obj(r, 0);
    if (out != nullptr && r.pos != r.n) {
        Py_DECREF(out);
        out = nullptr;
        PyErr_SetString(PyExc_ValueError,
                        "trailing bytes after binary frame");
    }
    PyBuffer_Release(&view);
    return out;
}

PyObject *wire_unpack(PyObject *, PyObject *args) {
    Py_buffer view;
    if (!PyArg_ParseTuple(args, "y*", &view)) return nullptr;
    Reader r{(const unsigned char *)view.buf, view.len};
    PyObject *out = unpack_value(r, 0);
    if (out != nullptr && r.pos != r.n) {
        Py_DECREF(out);
        out = nullptr;
        PyErr_SetString(PyExc_ValueError,
                        "trailing bytes after binary frame");
    }
    PyBuffer_Release(&view);
    return out;
}

/* wire_bind(ts, txnid, ballot, key, rkey, keys, rkeys, enum_base,
 *           registry_provider, slots_of, py_encode)
 * Arms the raw-object packer with the primitive classes and the lazy
 * verb-registry/slots helpers.  Without a bind, pack falls back to the
 * Python structural walk for every non-tree object. */
PyObject *wire_bind(PyObject *, PyObject *args) {
    PyObject *ts, *txnid, *ballot, *key, *rkey, *keys, *rkeys, *enum_base,
        *provider, *slots_of, *py_encode;
    if (!PyArg_ParseTuple(args, "OOOOOOOOOOO", &ts, &txnid, &ballot, &key,
                          &rkey, &keys, &rkeys, &enum_base, &provider,
                          &slots_of, &py_encode))
        return nullptr;
    Py_XDECREF(g_ts); Py_XDECREF(g_txnid); Py_XDECREF(g_ballot);
    Py_XDECREF(g_key); Py_XDECREF(g_rkey); Py_XDECREF(g_keys);
    Py_XDECREF(g_rkeys); Py_XDECREF(g_enum_base);
    Py_XDECREF(g_registry_provider); Py_XDECREF(g_slots_of);
    Py_XDECREF(g_py_encode); Py_XDECREF(g_registry);
    g_registry = nullptr;
    Py_INCREF(ts); g_ts = ts;
    Py_INCREF(txnid); g_txnid = txnid;
    Py_INCREF(ballot); g_ballot = ballot;
    Py_INCREF(key); g_key = key;
    Py_INCREF(rkey); g_rkey = rkey;
    Py_INCREF(keys); g_keys = keys;
    Py_INCREF(rkeys); g_rkeys = rkeys;
    Py_INCREF(enum_base); g_enum_base = enum_base;
    Py_INCREF(provider); g_registry_provider = provider;
    Py_INCREF(slots_of); g_slots_of = slots_of;
    Py_INCREF(py_encode); g_py_encode = py_encode;
    if (g_slots_cache == nullptr) g_slots_cache = PyDict_New();
    if (s_epoch == nullptr) {
        s_epoch = PyUnicode_InternFromString("epoch");
        s_hlc = PyUnicode_InternFromString("hlc");
        s_flags = PyUnicode_InternFromString("flags");
        s_node = PyUnicode_InternFromString("node");
        s_token = PyUnicode_InternFromString("token");
        s_keys_attr = PyUnicode_InternFromString("_keys");
        s_dict_attr = PyUnicode_InternFromString("__dict__");
        s_value_attr = PyUnicode_InternFromString("value");
        s_name_attr = PyUnicode_InternFromString("__name__");
        s_unpack_attr = PyUnicode_InternFromString("unpack");
        s_new_attr = PyUnicode_InternFromString("__new__");
        s_presorted_kw = PyUnicode_InternFromString("_presorted");
    }
    Py_RETURN_NONE;
}

PyMethodDef methods[] = {
    {"wire_pack", wire_pack, METH_VARARGS,
     "pack one structural wire tree (or raw payload objects) into "
     "tagged binary"},
    {"wire_unpack", wire_unpack, METH_VARARGS,
     "unpack tagged binary into the structural wire tree"},
    {"wire_unpack_obj", wire_unpack_obj, METH_VARARGS,
     "unpack tagged binary straight into decoded frame/message objects"},
    {"wire_bind", wire_bind, METH_VARARGS,
     "bind primitive classes + registry/slots helpers for the raw-object "
     "packer"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_accord_wire",
    "native binary wire frame codec", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

extern "C" PyMODINIT_FUNC PyInit__accord_wire(void) {
    return PyModule_Create(&moduledef);
}
