"""Native tier: C++ kernels for the protocol engine's hottest host loops.

Built lazily on first import: each source under this package is compiled
with the ambient C++ toolchain into a cached shared object next to this
file and loaded as its own module — `_sorted_arrays.cpp` (the
SortedArrays/CINTIA kernels, `get()`) and `_wire_codec.cpp` (the binary
wire frame codec, `get_wire()`).  Absence of a compiler (or any build/load
failure) degrades silently to the pure-Python tier — the implementations
are behaviourally identical (tests/test_sorted_arrays.py runs against
whichever is active, test_native.py cross-checks the sorted-array tiers,
and tests/test_wire_roundtrip.py pins the wire codec tiers byte-identical).

Rebuilds happen automatically when a source is newer than its cached
object.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig

AVAILABLE = False
_mod = None
_wire_mod = None
_wire_tried = False
_cfk_mod = None
_cfk_tried = False


def _build_and_load(src_name: str, mod_name: str):
    here = os.path.dirname(__file__)
    src = os.path.join(here, src_name)
    out = os.path.join(here, f"{mod_name}_{sys.version_info.major}"
                             f"{sys.version_info.minor}.so")
    if not os.path.exists(out) \
            or os.path.getmtime(out) < os.path.getmtime(src):
        include = sysconfig.get_paths()["include"]
        cxx = os.environ.get("CXX", "g++")
        # per-process temp name: concurrent first imports (multi-process
        # runner, pytest-xdist) must not interleave writes before the
        # atomic replace
        tmp = f"{out}.{os.getpid()}.tmp"
        cmd = [cxx, "-O2", "-shared", "-fPIC", f"-I{include}", src, "-o",
               tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
    spec = importlib.util.spec_from_file_location(mod_name, out)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


if os.environ.get("ACCORD_NO_NATIVE", "") != "1":
    try:
        _mod = _build_and_load("_sorted_arrays.cpp", "_accord_native")
        AVAILABLE = True
    except Exception:  # noqa: BLE001 — any failure means Python tier
        _mod = None
        AVAILABLE = False


def get():
    """The native sorted-array module, or None (Python tier)."""
    return _mod


def get_wire():
    """The native wire-codec module, or None (Python tier).  Built on
    first call rather than at import: only frame-transport hosts pay the
    (cached) compile, not every `import accord_tpu.native`."""
    global _wire_mod, _wire_tried
    if not _wire_tried:
        _wire_tried = True
        if os.environ.get("ACCORD_NO_NATIVE", "") != "1":
            try:
                _wire_mod = _build_and_load("_wire_codec.cpp",
                                            "_accord_wire")
            except Exception:  # noqa: BLE001 — Python tier fallback
                _wire_mod = None
    return _wire_mod


def get_cfk():
    """The native CommandsForKey core (_cfk_core.cpp), or None (Python
    tier).  Built lazily like the wire codec.  Tier selection:
    ``ACCORD_NATIVE=0`` (the CFK-tier knob) or ``ACCORD_NO_NATIVE=1`` (the
    package-wide kill switch) force the bit-identical Python tier; any
    build/load failure degrades to it silently."""
    global _cfk_mod, _cfk_tried
    if not _cfk_tried:
        _cfk_tried = True
        if os.environ.get("ACCORD_NO_NATIVE", "") != "1" \
                and os.environ.get("ACCORD_NATIVE", "") != "0":
            try:
                _cfk_mod = _build_and_load("_cfk_core.cpp", "_accord_cfk")
            except Exception:  # noqa: BLE001 — Python tier fallback
                _cfk_mod = None
    return _cfk_mod
