"""Native tier: C++ kernels for the protocol engine's hottest host loops.

Built lazily on first import: `_sorted_arrays.cpp` is compiled with the
ambient C++ toolchain into a cached shared object next to this file and
loaded as `_accord_native`. Absence of a compiler (or any build/load
failure) degrades silently to the pure-Python tier — the implementations
are behaviourally identical (tests/test_sorted_arrays.py runs against
whichever is active, and test_native.py cross-checks the two).

Rebuilds happen automatically when the source is newer than the cached
object.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig

AVAILABLE = False
_mod = None


def _build_and_load():
    here = os.path.dirname(__file__)
    src = os.path.join(here, "_sorted_arrays.cpp")
    out = os.path.join(here, f"_accord_native_{sys.version_info.major}"
                             f"{sys.version_info.minor}.so")
    if not os.path.exists(out) \
            or os.path.getmtime(out) < os.path.getmtime(src):
        include = sysconfig.get_paths()["include"]
        cxx = os.environ.get("CXX", "g++")
        # per-process temp name: concurrent first imports (multi-process
        # runner, pytest-xdist) must not interleave writes before the
        # atomic replace
        tmp = f"{out}.{os.getpid()}.tmp"
        cmd = [cxx, "-O2", "-shared", "-fPIC", f"-I{include}", src, "-o",
               tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
    spec = importlib.util.spec_from_file_location("_accord_native", out)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


if os.environ.get("ACCORD_NO_NATIVE", "") != "1":
    try:
        _mod = _build_and_load()
        AVAILABLE = True
    except Exception:  # noqa: BLE001 — any failure means Python tier
        _mod = None
        AVAILABLE = False


def get():
    """The native module, or None when running on the Python tier."""
    return _mod
