/* Native CommandsForKey core loops — PAPER.md's north-star kernel #1.
 *
 * Reference: accord/local/CommandsForKey.java:652-1000 (incremental update
 * with missing[] maintenance), :738-860 (the additions path installing an
 * entry's own divergence), :614-650 (mapReduceActive — the deps scan).
 *
 * The packed parallel arrays (_ids/_status/_eat/_missing/_wdeps) stay plain
 * Python lists owned by accord_tpu/local/cfk.CommandsForKey — the shared
 * authoritative representation both tiers (and the device encoder) read —
 * and this module owns the three hot LOOPS over them, each one C pass where
 * the Python tier pays an interpreted iteration per entry:
 *
 *   add_missing_everywhere — the per-insert walk recording a new id's
 *       divergence in every bounded entry's missing[]
 *   remove_missing         — the per-commit elision walk over missing[]
 *   apply_deps             — the additions insert + own-missing[] install
 *       (replacing the per-call set()/sorted() allocations)
 *   map_reduce_active      — the deps scan with transitive elision
 *
 * BIT-IDENTITY CONTRACT (same precedent as _wire_codec.cpp): every function
 * must leave the arrays in exactly the state the Python tier would — the
 * differential suite (tests/test_cfk_native.py) cross-checks randomized op
 * sequences tier-against-tier, and ops/deps_kernel's batched device path is
 * pinned bit-identical to whichever tier is live.
 *
 * Ordering rides each Timestamp's precomputed `_cmp` packed key (an int —
 * CPython long compares are C-level), never the Python-defined __lt__;
 * kind/domain tests decode `flags` exactly like timestamp.py's lookup
 * tables, with the witness matrix passed IN from the single source of truth
 * (timestamp._WITNESS_BITS), never duplicated here.
 *
 * Built on first use by accord_tpu/native/__init__.get_cfk(); any build or
 * load failure (or ACCORD_NATIVE=0 / ACCORD_NO_NATIVE=1) degrades to the
 * behaviourally identical Python tier.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

namespace {

PyObject *s_cmp = nullptr;    /* interned "_cmp" */
PyObject *s_flags = nullptr;  /* interned "flags" */

/* InternalStatus bands (accord_tpu.local.cfk.InternalStatus) */
constexpr long ST_TRANSITIVELY_KNOWN = 0;
constexpr long ST_ACCEPTED = 3;   /* has_info low bound */
constexpr long ST_COMMITTED = 4;
constexpr long ST_APPLIED = 6;    /* has_info / is_committed high bound */
constexpr long ST_INVALID = 7;

inline bool has_info(long s) { return s >= ST_ACCEPTED && s <= ST_APPLIED; }
inline bool is_committed(long s) { return s >= ST_COMMITTED && s <= ST_APPLIED; }
inline bool is_decided(long s) { return s >= ST_COMMITTED; }

/* flags bit layout (timestamp.py): domain = bit 0, kind = bits 1..3 */
inline long kind_of(long flags) { return (flags >> 1) & 0x7; }
inline bool is_key_domain(long flags) { return (flags & 1) == 0; }
inline bool kind_is_write(long flags) {
    long k = kind_of(flags);
    return k == 2 || k == 5;  /* WRITE, EXCLUSIVE_SYNC_POINT */
}

/* new ref to o._cmp (the packed total-order int), or null on error */
inline PyObject *get_cmp(PyObject *o) { return PyObject_GetAttr(o, s_cmp); }

inline long get_flags(PyObject *o, bool *err) {
    PyObject *f = PyObject_GetAttr(o, s_flags);
    if (f == nullptr) { *err = true; return 0; }
    long v = PyLong_AsLong(f);
    Py_DECREF(f);
    if (v == -1 && PyErr_Occurred()) { *err = true; return 0; }
    return v;
}

/* a <op> b via rich comparison of the (long) cmp keys; -1 on error */
inline int cmp_bool(PyObject *a_cmp, PyObject *b_cmp, int op) {
    return PyObject_RichCompareBool(a_cmp, b_cmp, op);
}

/* entry j's deps-known-before bound: eat[j] while committed with a
 * recorded executeAt, its own id otherwise (InternalStatus.depsKnownBefore) */
inline PyObject *bound_of(PyObject *ids, PyObject *eat, Py_ssize_t j, long s) {
    PyObject *e = PyList_GET_ITEM(eat, j);
    if (is_committed(s) && e != Py_None) return e;
    return PyList_GET_ITEM(ids, j);
}

/* eat[i] if set else ids[i] (CommandsForKey._eat_of) */
inline PyObject *eat_of(PyObject *ids, PyObject *eat, Py_ssize_t i) {
    PyObject *e = PyList_GET_ITEM(eat, i);
    return e != Py_None ? e : PyList_GET_ITEM(ids, i);
}

inline long status_at(PyObject *status, Py_ssize_t j, bool *err) {
    long v = PyLong_AsLong(PyList_GET_ITEM(status, j));
    if (v == -1 && PyErr_Occurred()) { *err = true; }
    return v;
}

/* bisect_left over a list/tuple of timestamps by cmp key.
 * target_cmp is the probe's _cmp int. -1 on error. */
Py_ssize_t bisect_left_cmp(PyObject *seq, bool is_list, PyObject *target_cmp,
                           Py_ssize_t hi_in = -1) {
    Py_ssize_t lo = 0;
    Py_ssize_t hi = hi_in >= 0 ? hi_in
        : (is_list ? PyList_GET_SIZE(seq) : PyTuple_GET_SIZE(seq));
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) / 2;
        PyObject *item = is_list ? PyList_GET_ITEM(seq, mid)
                                 : PyTuple_GET_ITEM(seq, mid);
        PyObject *c = get_cmp(item);
        if (c == nullptr) return -1;
        int lt = cmp_bool(c, target_cmp, Py_LT);
        Py_DECREF(c);
        if (lt < 0) return -1;
        if (lt) lo = mid + 1; else hi = mid;
    }
    return lo;
}

/* does sorted tuple m contain an element with cmp == target_cmp?
 * out_idx receives the insertion point. -1 err / 0 no / 1 yes. */
int tuple_find_cmp(PyObject *m, PyObject *target_cmp, Py_ssize_t *out_idx) {
    Py_ssize_t k = bisect_left_cmp(m, false, target_cmp);
    if (k < 0) return -1;
    *out_idx = k;
    if (k >= PyTuple_GET_SIZE(m)) return 0;
    PyObject *c = get_cmp(PyTuple_GET_ITEM(m, k));
    if (c == nullptr) return -1;
    int eq = cmp_bool(c, target_cmp, Py_EQ);
    Py_DECREF(c);
    return eq;
}

/* tuple copy of m with `item` spliced in at k */
PyObject *tuple_insert(PyObject *m, Py_ssize_t k, PyObject *item) {
    Py_ssize_t n = PyTuple_GET_SIZE(m);
    PyObject *out = PyTuple_New(n + 1);
    if (out == nullptr) return nullptr;
    for (Py_ssize_t i = 0; i < k; ++i) {
        PyObject *v = PyTuple_GET_ITEM(m, i);
        Py_INCREF(v);
        PyTuple_SET_ITEM(out, i, v);
    }
    Py_INCREF(item);
    PyTuple_SET_ITEM(out, k, item);
    for (Py_ssize_t i = k; i < n; ++i) {
        PyObject *v = PyTuple_GET_ITEM(m, i);
        Py_INCREF(v);
        PyTuple_SET_ITEM(out, i + 1, v);
    }
    return out;
}

/* tuple copy of m without index k */
PyObject *tuple_remove(PyObject *m, Py_ssize_t k) {
    Py_ssize_t n = PyTuple_GET_SIZE(m);
    PyObject *out = PyTuple_New(n - 1);
    if (out == nullptr) return nullptr;
    for (Py_ssize_t i = 0; i < k; ++i) {
        PyObject *v = PyTuple_GET_ITEM(m, i);
        Py_INCREF(v);
        PyTuple_SET_ITEM(out, i, v);
    }
    for (Py_ssize_t i = k + 1; i < n; ++i) {
        PyObject *v = PyTuple_GET_ITEM(m, i);
        Py_INCREF(v);
        PyTuple_SET_ITEM(out, i - 1, v);
    }
    return out;
}

/* witness-bit table handed in from timestamp._WITNESS_BITS (8 ints) */
bool load_witness_bits(PyObject *wb_obj, long wb[8]) {
    if (!PyTuple_Check(wb_obj) || PyTuple_GET_SIZE(wb_obj) != 8) {
        PyErr_SetString(PyExc_TypeError, "witness_bits must be an 8-tuple");
        return false;
    }
    for (int i = 0; i < 8; ++i) {
        wb[i] = PyLong_AsLong(PyTuple_GET_ITEM(wb_obj, i));
        if (wb[i] == -1 && PyErr_Occurred()) return false;
    }
    return true;
}

/* ---- add_missing_everywhere: record a newly-witnessed undecided id in
 * every bounded has_info entry's missing[] (insertInfoAndOneMissing,
 * CommandsForKey.java:897-960).  Shared by the exported entry point and
 * apply_deps' additions path. */
int add_missing_impl(PyObject *ids, PyObject *status, PyObject *eat,
                     PyObject *missing, PyObject *new_id, const long wb[8]) {
    PyObject *new_cmp = get_cmp(new_id);
    if (new_cmp == nullptr) return -1;
    bool err = false;
    long new_flags = get_flags(new_id, &err);
    if (err) { Py_DECREF(new_cmp); return -1; }
    long new_kind = kind_of(new_flags);
    Py_ssize_t n = PyList_GET_SIZE(ids);
    for (Py_ssize_t j = 0; j < n; ++j) {
        long s = status_at(status, j, &err);
        if (err) { Py_DECREF(new_cmp); return -1; }
        if (!has_info(s)) continue;
        PyObject *entry = PyList_GET_ITEM(ids, j);
        PyObject *entry_cmp = get_cmp(entry);
        if (entry_cmp == nullptr) { Py_DECREF(new_cmp); return -1; }
        int eq = cmp_bool(entry_cmp, new_cmp, Py_EQ);
        Py_DECREF(entry_cmp);
        if (eq < 0) { Py_DECREF(new_cmp); return -1; }
        if (eq) continue;
        long entry_flags = get_flags(entry, &err);
        if (err) { Py_DECREF(new_cmp); return -1; }
        if (!((wb[kind_of(entry_flags)] >> new_kind) & 1)) continue;
        PyObject *bound = bound_of(ids, eat, j, s);
        PyObject *bound_cmp = get_cmp(bound);
        if (bound_cmp == nullptr) { Py_DECREF(new_cmp); return -1; }
        int gt = cmp_bool(bound_cmp, new_cmp, Py_GT);
        Py_DECREF(bound_cmp);
        if (gt < 0) { Py_DECREF(new_cmp); return -1; }
        if (!gt) continue;
        PyObject *m = PyList_GET_ITEM(missing, j);
        Py_ssize_t k;
        int found = tuple_find_cmp(m, new_cmp, &k);
        if (found < 0) { Py_DECREF(new_cmp); return -1; }
        if (found) continue;
        PyObject *grown = tuple_insert(m, k, new_id);
        if (grown == nullptr) { Py_DECREF(new_cmp); return -1; }
        PyList_SetItem(missing, j, grown);  /* steals grown, drops old m */
    }
    Py_DECREF(new_cmp);
    return 0;
}

PyObject *add_missing_everywhere(PyObject *, PyObject *args) {
    PyObject *ids, *status, *eat, *missing, *new_id, *wb_obj;
    if (!PyArg_ParseTuple(args, "O!O!O!O!OO", &PyList_Type, &ids,
                          &PyList_Type, &status, &PyList_Type, &eat,
                          &PyList_Type, &missing, &new_id, &wb_obj))
        return nullptr;
    long wb[8];
    if (!load_witness_bits(wb_obj, wb)) return nullptr;
    if (add_missing_impl(ids, status, eat, missing, new_id, wb) < 0)
        return nullptr;
    Py_RETURN_NONE;
}

/* ---- remove_missing: elide a newly-committed id from every missing
 * collection (removeMissing, CommandsForKey.java:962-987) */
PyObject *remove_missing(PyObject *, PyObject *args) {
    PyObject *missing, *txn_id;
    if (!PyArg_ParseTuple(args, "O!O", &PyList_Type, &missing, &txn_id))
        return nullptr;
    PyObject *cmp = get_cmp(txn_id);
    if (cmp == nullptr) return nullptr;
    Py_ssize_t n = PyList_GET_SIZE(missing);
    for (Py_ssize_t j = 0; j < n; ++j) {
        PyObject *m = PyList_GET_ITEM(missing, j);
        if (PyTuple_GET_SIZE(m) == 0) continue;
        Py_ssize_t k;
        int found = tuple_find_cmp(m, cmp, &k);
        if (found < 0) { Py_DECREF(cmp); return nullptr; }
        if (!found) continue;
        PyObject *shrunk = tuple_remove(m, k);
        if (shrunk == nullptr) { Py_DECREF(cmp); return nullptr; }
        PyList_SetItem(missing, j, shrunk);
    }
    Py_DECREF(cmp);
    Py_RETURN_NONE;
}

/* one parsed dep: borrowed object + owned cmp + flags */
struct Dep {
    PyObject *obj;
    PyObject *cmp;
    long flags;
};

void free_deps(Dep *d, Py_ssize_t n) {
    for (Py_ssize_t i = 0; i < n; ++i) Py_XDECREF(d[i].cmp);
    PyMem_Free(d);
}

/* binary search the sorted unique dep array for target_cmp */
int deps_contains(const Dep *deps, Py_ssize_t n, PyObject *target_cmp) {
    Py_ssize_t lo = 0, hi = n;
    while (lo < hi) {
        Py_ssize_t mid = (lo + hi) / 2;
        int lt = cmp_bool(deps[mid].cmp, target_cmp, Py_LT);
        if (lt < 0) return -1;
        if (lt) { lo = mid + 1; continue; }
        int eq = cmp_bool(deps[mid].cmp, target_cmp, Py_EQ);
        if (eq < 0) return -1;
        if (eq) return 1;
        hi = mid;
    }
    return 0;
}

/* ---- apply_deps: install an entry's own missing[] divergence + wdeps and
 * insert any dep ids never witnessed here as TRANSITIVELY_KNOWN (the
 * additions path, CommandsForKey.java:738-860).
 *
 * apply_deps(ids, status, eat, missing, wdeps, txn_id, status_int,
 *            dep_ids, tk_status, witness_bits)
 *   tk_status: the InternalStatus.TRANSITIVELY_KNOWN enum member, inserted
 *   verbatim so the status list stays homogeneous with the Python tier. */
PyObject *apply_deps(PyObject *, PyObject *args) {
    PyObject *ids, *status, *eat, *missing, *wdeps, *txn_id, *dep_obj,
        *tk_status, *wb_obj;
    long status_int;
    if (!PyArg_ParseTuple(args, "O!O!O!O!O!OlOOO", &PyList_Type, &ids,
                          &PyList_Type, &status, &PyList_Type, &eat,
                          &PyList_Type, &missing, &PyList_Type, &wdeps,
                          &txn_id, &status_int, &dep_obj, &tk_status,
                          &wb_obj))
        return nullptr;
    long wb[8];
    if (!load_witness_bits(wb_obj, wb)) return nullptr;

    PyObject *dep_seq = PySequence_Fast(dep_obj, "dep_ids must be a sequence");
    if (dep_seq == nullptr) return nullptr;
    Py_ssize_t raw_n = PySequence_Fast_GET_SIZE(dep_seq);
    Dep *deps = (Dep *)PyMem_Malloc(sizeof(Dep) * (raw_n ? raw_n : 1));
    if (deps == nullptr) { Py_DECREF(dep_seq); PyErr_NoMemory(); return nullptr; }
    Py_ssize_t dn = 0;
    bool err = false;
    for (Py_ssize_t i = 0; i < raw_n && !err; ++i) {
        PyObject *o = PySequence_Fast_GET_ITEM(dep_seq, i);
        PyObject *c = get_cmp(o);
        if (c == nullptr) { err = true; break; }
        long f = get_flags(o, &err);
        if (err) { Py_DECREF(c); break; }
        deps[dn].obj = o; deps[dn].cmp = c; deps[dn].flags = f;
        ++dn;
    }
    if (err) { free_deps(deps, dn); Py_DECREF(dep_seq); return nullptr; }
    /* sort ascending by cmp (dep lists arrive near-sorted from the CSR, so
     * insertion sort is ~linear), then dedup equal keys — the Python
     * tier's set() + sorted() */
    for (Py_ssize_t i = 1; i < dn && !err; ++i) {
        Dep cur = deps[i];
        Py_ssize_t j = i;
        while (j > 0) {
            int lt = cmp_bool(cur.cmp, deps[j - 1].cmp, Py_LT);
            if (lt < 0) { err = true; break; }
            if (!lt) break;
            deps[j] = deps[j - 1];
            --j;
        }
        deps[j] = cur;
    }
    if (!err && dn > 1) {
        Py_ssize_t w = 1;
        for (Py_ssize_t i = 1; i < dn; ++i) {
            int eq = cmp_bool(deps[i].cmp, deps[w - 1].cmp, Py_EQ);
            if (eq < 0) { err = true; break; }
            if (eq) { Py_DECREF(deps[i].cmp); continue; }
            deps[w++] = deps[i];
        }
        if (!err) dn = w;
    }
    if (err) { free_deps(deps, dn); Py_DECREF(dep_seq); return nullptr; }

    PyObject *empty = PyTuple_New(0);
    if (empty == nullptr) { free_deps(deps, dn); Py_DECREF(dep_seq); return nullptr; }

    /* additions: key-domain deps this key never witnessed enter all five
     * arrays as TRANSITIVELY_KNOWN, each followed by its own missing[]
     * walk — exactly the Python tier's per-addition _insert order */
    for (Py_ssize_t i = 0; i < dn && !err; ++i) {
        if (!is_key_domain(deps[i].flags)) continue;
        Py_ssize_t p = bisect_left_cmp(ids, true, deps[i].cmp);
        if (p < 0) { err = true; break; }
        if (p < PyList_GET_SIZE(ids)) {
            PyObject *c = get_cmp(PyList_GET_ITEM(ids, p));
            if (c == nullptr) { err = true; break; }
            int eq = cmp_bool(c, deps[i].cmp, Py_EQ);
            Py_DECREF(c);
            if (eq < 0) { err = true; break; }
            if (eq) continue;  /* already witnessed */
        }
        if (PyList_Insert(ids, p, deps[i].obj) < 0
            || PyList_Insert(status, p, tk_status) < 0
            || PyList_Insert(eat, p, Py_None) < 0
            || PyList_Insert(missing, p, empty) < 0
            || PyList_Insert(wdeps, p, empty) < 0) { err = true; break; }
        if (add_missing_impl(ids, status, eat, missing, deps[i].obj, wb) < 0) {
            err = true; break;
        }
    }
    if (err) {
        Py_DECREF(empty); free_deps(deps, dn); Py_DECREF(dep_seq);
        return nullptr;
    }

    /* own missing[]: every undecided witnessed id below the deps-known
     * bound that our kind witnesses but the dep set omits */
    PyObject *txn_cmp = get_cmp(txn_id);
    long txn_flags = txn_cmp != nullptr ? get_flags(txn_id, &err) : 0;
    if (txn_cmp == nullptr || err) {
        Py_XDECREF(txn_cmp); Py_DECREF(empty);
        free_deps(deps, dn); Py_DECREF(dep_seq);
        return nullptr;
    }
    long txn_wbits = wb[kind_of(txn_flags)];
    PyObject *out = nullptr, *result = nullptr;
    Py_ssize_t pos = bisect_left_cmp(ids, true, txn_cmp);
    if (pos < 0) goto fail;
    {
        /* pos references txn_id itself (update inserted it before this
         * call); bound = deps-known-before under the NEW status: the
         * recorded eat while committed, the id otherwise */
        PyObject *e = PyList_GET_ITEM(eat, pos);
        PyObject *bound = (is_committed(status_int) && e != Py_None)
            ? e : txn_id;
        PyObject *bound_cmp = get_cmp(bound);
        if (bound_cmp == nullptr) goto fail;
        Py_ssize_t hi = bisect_left_cmp(ids, true, bound_cmp);
        Py_DECREF(bound_cmp);
        if (hi < 0) goto fail;
        out = PyList_New(0);
        if (out == nullptr) goto fail;
        for (Py_ssize_t j = 0; j < hi; ++j) {
            if (j == pos) continue;
            long s = status_at(status, j, &err);
            if (err) goto fail;
            if (is_decided(s)) continue;  /* elided: committed visible */
            PyObject *t = PyList_GET_ITEM(ids, j);
            long tf = get_flags(t, &err);
            if (err) goto fail;
            if (!((txn_wbits >> kind_of(tf)) & 1)) continue;
            PyObject *tc = get_cmp(t);
            if (tc == nullptr) goto fail;
            int in_deps = deps_contains(deps, dn, tc);
            Py_DECREF(tc);
            if (in_deps < 0) goto fail;
            if (in_deps) continue;
            if (PyList_Append(out, t) < 0) goto fail;
        }
        PyObject *mt = PyList_AsTuple(out);
        if (mt == nullptr) goto fail;
        PyList_SetItem(missing, pos, mt);
        Py_CLEAR(out);
        /* wdeps: the registered key-domain WRITE deps, sorted unique */
        Py_ssize_t wn = 0;
        for (Py_ssize_t i = 0; i < dn; ++i)
            if (is_key_domain(deps[i].flags) && kind_is_write(deps[i].flags))
                ++wn;
        PyObject *wt = PyTuple_New(wn);
        if (wt == nullptr) goto fail;
        Py_ssize_t w = 0;
        for (Py_ssize_t i = 0; i < dn; ++i) {
            if (!(is_key_domain(deps[i].flags) && kind_is_write(deps[i].flags)))
                continue;
            Py_INCREF(deps[i].obj);
            PyTuple_SET_ITEM(wt, w++, deps[i].obj);
        }
        PyList_SetItem(wdeps, pos, wt);
    }
    result = Py_None;
    Py_INCREF(result);
fail:
    Py_XDECREF(out);
    Py_DECREF(txn_cmp);
    Py_DECREF(empty);
    free_deps(deps, dn);
    Py_DECREF(dep_seq);
    return result;
}

/* ---- map_reduce_active: the deps scan (mapReduceActive,
 * CommandsForKey.java:614-650).  Returns the visited ids as a list; the
 * caller computes the transitive-elision bound (a cheap bisect over the
 * committed view) and invokes its fn per element.
 *
 * map_reduce_active(ids, status, eat, before, kinds_mask, bound_or_None) */
PyObject *map_reduce_active(PyObject *, PyObject *args) {
    PyObject *ids, *status, *eat, *before, *bound;
    long kmask;
    if (!PyArg_ParseTuple(args, "O!O!O!OlO", &PyList_Type, &ids,
                          &PyList_Type, &status, &PyList_Type, &eat,
                          &before, &kmask, &bound))
        return nullptr;
    PyObject *before_cmp = get_cmp(before);
    if (before_cmp == nullptr) return nullptr;
    Py_ssize_t hi = bisect_left_cmp(ids, true, before_cmp);
    Py_DECREF(before_cmp);
    if (hi < 0) return nullptr;
    PyObject *bound_cmp = nullptr;
    if (bound != Py_None) {
        bound_cmp = get_cmp(bound);
        if (bound_cmp == nullptr) return nullptr;
    }
    PyObject *out = PyList_New(0);
    if (out == nullptr) { Py_XDECREF(bound_cmp); return nullptr; }
    bool err = false;
    for (Py_ssize_t i = 0; i < hi; ++i) {
        PyObject *t = PyList_GET_ITEM(ids, i);
        long tf = get_flags(t, &err);
        if (err) goto fail;
        if (!((kmask >> kind_of(tf)) & 1)) continue;
        long s = status_at(status, i, &err);
        if (err) goto fail;
        if (s == ST_TRANSITIVELY_KNOWN || s == ST_INVALID) continue;
        if (is_committed(s) && bound_cmp != nullptr) {
            PyObject *ec = get_cmp(eat_of(ids, eat, i));
            if (ec == nullptr) goto fail;
            int lt = cmp_bool(ec, bound_cmp, Py_LT);
            Py_DECREF(ec);
            if (lt < 0) goto fail;
            if (lt) continue;  /* transitively covered by the bound write */
        }
        if (PyList_Append(out, t) < 0) goto fail;
    }
    Py_XDECREF(bound_cmp);
    return out;
fail:
    Py_XDECREF(bound_cmp);
    Py_DECREF(out);
    return nullptr;
}

/* ---- pos: Java-convention bisect over the ids list by packed cmp key
 * (match index, or -(insertion)-1) — CommandsForKey._pos without the
 * Python-level __lt__ dispatch per probe */
PyObject *pos(PyObject *, PyObject *args) {
    PyObject *ids, *target;
    if (!PyArg_ParseTuple(args, "O!O", &PyList_Type, &ids, &target))
        return nullptr;
    PyObject *tc = get_cmp(target);
    if (tc == nullptr) return nullptr;
    Py_ssize_t i = bisect_left_cmp(ids, true, tc);
    if (i < 0) { Py_DECREF(tc); return nullptr; }
    if (i < PyList_GET_SIZE(ids)) {
        PyObject *c = get_cmp(PyList_GET_ITEM(ids, i));
        if (c == nullptr) { Py_DECREF(tc); return nullptr; }
        int eq = cmp_bool(c, tc, Py_EQ);
        Py_DECREF(c);
        Py_DECREF(tc);
        if (eq < 0) return nullptr;
        return PyLong_FromSsize_t(eq ? i : -i - 1);
    }
    Py_DECREF(tc);
    return PyLong_FromSsize_t(-i - 1);
}

PyMethodDef methods[] = {
    {"add_missing_everywhere", add_missing_everywhere, METH_VARARGS,
     "record a newly-witnessed undecided id in every bounded missing[]"},
    {"pos", pos, METH_VARARGS,
     "Java-convention bisect over sorted timestamps by packed cmp key"},
    {"remove_missing", remove_missing, METH_VARARGS,
     "elide a newly-committed id from every missing collection"},
    {"apply_deps", apply_deps, METH_VARARGS,
     "install an entry's missing[] divergence, wdeps and dep additions"},
    {"map_reduce_active", map_reduce_active, METH_VARARGS,
     "the active-conflict deps scan with transitive elision"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_accord_cfk",
    "native CommandsForKey core loops", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

extern "C" PyMODINIT_FUNC PyInit__accord_cfk(void) {
    s_cmp = PyUnicode_InternFromString("_cmp");
    s_flags = PyUnicode_InternFromString("flags");
    if (s_cmp == nullptr || s_flags == nullptr) return nullptr;
    return PyModule_Create(&moduledef);
}
