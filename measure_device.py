#!/usr/bin/env python
"""Device-tier measurement harness: the three experiments VERDICT r4 asked
for (#3 wavefront A/B, #4 flush-window latency tax, #5 hit-rate vs
contention), producing the BASELINE.md tables.

Each experiment runs same-seed in-process BurnRuns (deterministic
discrete-event simulator: latencies are VIRTUAL time, immune to host load)
across its arms and prints a markdown table.

Usage: python measure_device.py [waves|latency|hitrate|all]
       (JAX_PLATFORMS=cpu recommended; measures logic, not the tunnel)
"""

from __future__ import annotations

import json
import sys

from accord_tpu.local import commands
from accord_tpu.sim.burn import BurnRun

SEEDS = (9101, 9102, 9103)
OPS = 150


def run_burn(seed, *, store_factory=None, keys=20, drop=0.10,
             partitions=True, stores=2, ops=OPS):
    commands.reset_work_counters()
    run = BurnRun(seed, ops, nodes=3, keys=keys, n_shards=4,
                  drop_prob=drop, partitions=partitions,
                  num_command_stores=stores, store_factory=store_factory)
    stats = run.run()
    work = dict(commands.WORK)
    dev = {}
    for node in run.cluster.nodes.values():
        for s in node.command_stores.all():
            for attr in ("device_hits", "device_misses",
                         "device_recovery_hits", "device_recovery_misses",
                         "device_range_hits", "device_range_misses",
                         "device_wave_batches", "device_wave_planned",
                         "device_wave_executed"):
                if hasattr(s, attr):
                    dev[attr] = dev.get(attr, 0) + getattr(s, attr)
    return {
        "acks": stats.acks, "nacks": stats.nacks,
        "p50_ms": stats.latency_us(50) / 1e3,
        "p95_ms": stats.latency_us(95) / 1e3,
        "p99_ms": stats.latency_us(99) / 1e3,
        "events": run.cluster.queue.processed,
        "virtual_s": run.cluster.now_s,
        "work": work, "dev": dev,
    }


def avg(rows, key_fn):
    vals = [key_fn(r) for r in rows]
    return sum(vals) / max(1, len(vals))


# ------------------------------------------------------------ experiment 1
def waves_ab():
    """Same-seed A/B: device store with the wavefront plan ON vs OFF.
    Reports the scalar listener-walk work (Commands WORK counters), wave
    stats, and client latency."""
    from accord_tpu.impl.device_store import DeviceCommandStore
    print("## Wavefront plan A/B (device store, same seeds, "
          f"{OPS} ops x {len(SEEDS)} seeds, 10% loss + partitions)\n")
    print("| arm | maybe_execute | notify | wave_planned | wave_executed |"
          " p50 ms | p95 ms | acks |")
    print("|---|---|---|---|---|---|---|---|")
    results = {}
    for label, plan in (("plan ON", True), ("plan OFF", False)):
        rows = [run_burn(s, store_factory=DeviceCommandStore.factory(
            flush_window_us=300, verify=True, plan_waves=plan))
            for s in SEEDS]
        results[label] = rows
        print(f"| {label} "
              f"| {avg(rows, lambda r: r['work']['maybe_execute']):.0f} "
              f"| {avg(rows, lambda r: r['work']['notify']):.0f} "
              f"| {avg(rows, lambda r: r['dev'].get('device_wave_planned', 0)):.0f} "
              f"| {avg(rows, lambda r: r['dev'].get('device_wave_executed', 0)):.0f} "
              f"| {avg(rows, lambda r: r['p50_ms']):.1f} "
              f"| {avg(rows, lambda r: r['p95_ms']):.1f} "
              f"| {avg(rows, lambda r: r['acks']):.1f} |")
    on = avg(results["plan ON"], lambda r: r["work"]["maybe_execute"])
    off = avg(results["plan OFF"], lambda r: r["work"]["maybe_execute"])
    delta = (on - off) / off * 100 if off else 0.0
    print(f"\nmaybe_execute delta plan-ON vs OFF: {delta:+.1f}%")
    return results


# ------------------------------------------------------------ experiment 2
def latency_tax():
    """Client-visible commit latency: scalar store vs device store at
    flush_window_us in {0, 300, 800}, same seeds (virtual time)."""
    from accord_tpu.impl.device_store import DeviceCommandStore
    print("## Flush-window latency tax (same seeds, virtual-time "
          f"latencies, {OPS} ops x {len(SEEDS)} seeds, 10% loss)\n")
    print("| store | p50 ms | p95 ms | p99 ms | acks |")
    print("|---|---|---|---|---|")
    arms = [("scalar", None)] + [
        (f"device fw={w}us", DeviceCommandStore.factory(
            flush_window_us=w, verify=True)) for w in (0, 300, 800)]
    out = {}
    for label, factory in arms:
        rows = [run_burn(s, store_factory=factory) for s in SEEDS]
        out[label] = rows
        print(f"| {label} | {avg(rows, lambda r: r['p50_ms']):.1f} "
              f"| {avg(rows, lambda r: r['p95_ms']):.1f} "
              f"| {avg(rows, lambda r: r['p99_ms']):.1f} "
              f"| {avg(rows, lambda r: r['acks']):.1f} |")
    return out


# ------------------------------------------------------------ experiment 3
def hit_rates():
    """Device-serve hit rates vs contention: keys in {4, 16, 64}."""
    from accord_tpu.impl.device_store import DeviceCommandStore
    print("## Device hit rates vs contention "
          f"({OPS} ops x {len(SEEDS)} seeds, 10% loss + partitions)\n")
    print("| keys | deps hit% | recovery hit% | range hit% | acks |")
    print("|---|---|---|---|---|")
    out = {}
    for keys in (4, 16, 64):
        rows = [run_burn(s, keys=keys,
                         store_factory=DeviceCommandStore.factory(
                             flush_window_us=300, verify=True))
                for s in SEEDS]
        out[keys] = rows

        def rate(h, m):
            th = sum(r["dev"].get(h, 0) for r in rows)
            tm = sum(r["dev"].get(m, 0) for r in rows)
            return 100.0 * th / max(1, th + tm)

        print(f"| {keys} "
              f"| {rate('device_hits', 'device_misses'):.1f} "
              f"| {rate('device_recovery_hits', 'device_recovery_misses'):.1f} "
              f"| {rate('device_range_hits', 'device_range_misses'):.1f} "
              f"| {avg(rows, lambda r: r['acks']):.1f} |")
    return out


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    from accord_tpu.utils.backend import resolve_platform
    platform = resolve_platform()
    print(f"platform: {platform}\n")
    results = {}
    if which in ("waves", "all"):
        results["waves"] = waves_ab()
        print()
    if which in ("latency", "all"):
        results["latency"] = latency_tax()
        print()
    if which in ("hitrate", "all"):
        results["hitrate"] = hit_rates()
    with open("/tmp/measure_device_raw.json", "w") as f:
        json.dump(results, f, default=str, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
