"""Replay the seed-15000-chain lost-append wedge and dump the blocking chain.

See SOAK_NOTES.md — run the chained seeds through one shared DelayedCommandStore
RandomSource; seed 15003 loses an acked append for key 1 (value 19).
"""
import sys
import traceback

from accord_tpu.sim.burn import BurnRun
from accord_tpu.sim.delayed_store import DelayedCommandStore
from accord_tpu.utils.random_source import RandomSource
from accord_tpu.primitives.timestamp import TxnId


def dump_chain(cluster, suspect_repr):
    """Walk every store on every node; dump the suspect's waiting_on and then
    the full blocking chain from it."""
    # find the suspect txn id by repr match
    suspect = None
    for node in cluster.nodes.values():
        for store in node.command_stores.stores:
            for txn_id in store.commands:
                if repr(txn_id) == suspect_repr:
                    suspect = txn_id
                    break
            if suspect:
                break
        if suspect:
            break
    if suspect is None:
        print("suspect not found by repr; dumping all PRE_APPLIED-but-unapplied")
        for node in cluster.nodes.values():
            for store in node.command_stores.stores:
                for txn_id, cmd in store.commands.items():
                    if cmd.save_status.name.startswith("PRE_APPLIED"):
                        print(node.id, store, txn_id, cmd.save_status.name)
        return

    # root blocker forensics
    root_repr = "W[1,1070,1]"
    for node in cluster.nodes.values():
        coords = {repr(t): v for t, v in node.coordinating.items()}
        print(f"n{node.id} coordinating: {sorted(coords)}")
        if root_repr in coords:
            res = coords[root_repr]
            print(f"   root-blocker future: done={getattr(res, 'is_done', '?')}"
                  f" cbs={len(getattr(res, '_callbacks', []) or [])}")
        for store in node.command_stores.stores:
            pl = store.progress_log
            for tid, st in list(getattr(pl, "blocked", {}).items()):
                if repr(tid) == root_repr:
                    print(f"   n{node.id} st{store.id} blocked[{tid!r}]: "
                          f"until={st.blocked_until} attempts={st.attempts} "
                          f"since={st.since_s:.1f} route={st.route} "
                          f"parts={st.participants}")
            cmd = store.commands.get(
                next((t for t in store.commands if repr(t) == root_repr), None))
            if cmd is not None:
                print(f"   n{node.id} st{store.id} root cmd route={cmd.route}")

    seen = set()
    frontier = [suspect]
    while frontier:
        tid = frontier.pop()
        if tid in seen:
            continue
        seen.add(tid)
        print(f"=== chain node {tid!r} ===")
        for node in cluster.nodes.values():
            for store in node.command_stores.stores:
                cmd = store.commands.get(tid)
                if cmd is None:
                    continue
                wo = cmd.waiting_on
                print(f"  n{node.id} st{store.id}: {cmd.save_status.name} "
                      f"at={cmd.execute_at} dur={cmd.durability.name} "
                      f"prom={cmd.promised} acc={cmd.accepted_ballot}")
                if wo is not None and wo.is_waiting:
                    wids = wo.waiting_ids()
                    wkeys = wo.waiting_key_list()
                    print(f"      waiting_on txns={wids} keys={wkeys}")
                    frontier.extend(wids)
                    # for waiting keys, look at the CFK to find what blocks
                    for k in wkeys:
                        cfk = store.cfks.get(k) if hasattr(store, "cfks") else None
                        if cfk is None and hasattr(store, "cfk"):
                            try:
                                cfk = store.cfk(k)
                            except Exception:
                                cfk = None
                        if cfk is not None:
                            print(f"      CFK[{k}]: {cfk!r}")


def main():
    factory = DelayedCommandStore.factory(RandomSource(15000 ^ 0x5D5D))
    for seed in (15000, 15001, 15002, 15003):
        run = BurnRun(seed, 400, nodes=3, keys=12, n_shards=2, drop_prob=0.22,
                      partitions=True, clock_drift=True, num_command_stores=4,
                      store_factory=factory)
        try:
            run.run()
            print(f"seed {seed}: OK")
        except Exception as e:
            print(f"seed {seed}: FAILED: {e}")
            traceback.print_exc(limit=3)
            dump_chain(run.cluster, "W[1,6088562,1]")
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
