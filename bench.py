"""Benchmark: conflict-graph edges resolved per second on the device tier.

Workload (BASELINE.md): synthetic Zipfian key contention — a window of
transactions over a Zipf(0.99) key universe with a deep per-key conflict
history, the shape of the reference's hot loop (CommandsForKey.mapReduceActive,
reference accord/local/CommandsForKey.java:614-650, invoked per key per
PreAccept).  The device resolves the whole window in one fused step: deps
masks + in-window conflict graph + MXU execution wavefront.

vs_baseline = speedup over the scalar host path on this machine (edges/s),
the stand-in for the reference's one-txn-at-a-time scan (the Java repo
publishes no numbers — BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Timing note (tunneled TPU platform): block_until_ready is NOT a reliable
sync there (measured returning early), a device->host pull costs a full
tunnel RTT (8-70 ms, variable), and after the first pull every dispatch
degrades to synchronous. Honest timing therefore folds repetition counts
INSIDE one jitted computation (iteration-skewed rolls deny loop-invariant
hoisting; the Pallas kernel folds reps into its grid) and differences a
small-rep call against a large-rep call, each made in the same post-pull
dispatch regime — RTT and dispatch overheads cancel exactly. The scanned
XLA timing bodies use the explicitly-XLA wavefront (pallas inside lax.scan
fails to lower here); the TPC-C config times the fused Pallas window
kernel via its reps-in-grid hook and labels the path in "kernel_path".

Extra BASELINE configs (not part of the driver's one-line contract):
    python bench.py --config rangestress # CINTIA interval-stabbing, host
    python bench.py --config slo-zipf1m  # 1M-key zipfian through the REAL
                                         # protocol path in bounded memory
                                         # (paging tier; retired the old
                                         # encoder-level zipf1m microbench)
"""

import argparse
import json
import os
import time

import numpy as np


PLATFORM = "unprobed"  # set by main() for device-using configs
JSON_OUT = None        # optional path: emit() mirrors the JSON line there
CONFIG = "default"     # set by main(); keys the regression-guard history
LAST_RESULT = None     # emit() stashes the row for --guard's comparison
ROWS_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_DEVICE_ROWS.json")
# ACCORD_BENCH_HISTORY overrides the history file (guard tests exercise the
# regression gate against a scratch history instead of the repo artifact)
HISTORY_PATH = os.environ.get(
    "ACCORD_BENCH_HISTORY",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_HISTORY.json"))


def _platform_class(platform: str) -> str:
    return "cpu" if platform.startswith("cpu") else "device"


# configs whose metric is a time/overhead (lower is better); everything
# else is a throughput (higher is better)
LOWER_IS_BETTER = {"tpcc", "audit", "slo-wan"}


def _regression_guard(result: dict) -> None:
    """Annotate the result with the last same-platform-class number for this
    config and flag regressions >10% — BENCH_r04's CPU number silently
    regressed 8% vs r03 with nobody noticing; never again.  Annotation, not
    assertion: the driver must still get its JSON line.  Host-tier configs
    (maelstrom/tcp) carry no platform field and are classed "host" — their
    wall-clock numbers are load-sensitive, so the annotation is a prompt to
    investigate, not proof of a code regression."""
    try:
        value = result.get("value")
        if not isinstance(value, (int, float)):
            return
        pclass = _platform_class(result["platform"]) \
            if result.get("platform") else "host"
        try:
            with open(HISTORY_PATH) as f:
                history = json.load(f)
        except (OSError, ValueError):
            history = {}
        prev = history.get(CONFIG, {}).get(pclass)
        if prev and prev.get("value"):
            result["prev_same_platform"] = prev
            pct = (value - prev["value"]) / prev["value"] * 100.0
            if CONFIG in LOWER_IS_BETTER:
                pct = -pct
            if pct < -10.0:
                result["REGRESSION_vs_prev_pct"] = round(pct, 1)
        entry = {
            "value": value, "platform": result.get("platform", "host"),
            "unix": int(time.time())}
        if "obs" in result:
            # metrics snapshot rides with the BENCH row (fast-path ratio,
            # per-phase latency histograms, device flush-window counts)
            entry["obs"] = result["obs"]
        if "profile" in result:
            # per-kernel p50/p99 + retrace summary (obs/profiler.py):
            # what `--guard` diffs against the last clean baseline
            entry["profile"] = result["profile"]
        if "slo" in result:
            # open-loop SLO report (workload/openloop.py): exact-sample
            # p50/p99/p99.9 overall and per phase — `--guard` gates the
            # tails, not just the headline throughput
            entry["slo"] = result["slo"]
        if "cpu" in result:
            # protocol-CPU waterfall (obs/cpuprof.py): per-(verb, stage)
            # exact-sample p50/p99 + the top-verbs table — `--guard`
            # gates per-verb p50 regressions like per-kernel p50s
            entry["cpu"] = result["cpu"]
        for key in ("per_shards", "per_procs", "cpus_available",
                    "scaling_first_to_last"):
            # multicore lane: the per-shard-count scaling table IS the
            # row's point — persist it next to the headline ("per_procs"
            # kept so pre-shard-runtime history rows still round-trip)
            if key in result:
                entry[key] = result[key]
        lane = history.setdefault(CONFIG, {})
        old = lane.get(pclass)
        if old is not None:
            # superseded rows are marked stale and retained (bounded), not
            # deleted — the provenance of every re-baseline stays auditable
            _supersede(lane, old, "overwritten by newer run")
        lane[pclass] = entry
        # pid-unique tmp: the --fill loop and interactive runs may emit
        # concurrently; a shared tmp path could interleave truncated JSON
        tmp = f"{HISTORY_PATH}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(history, f, indent=1)
        os.replace(tmp, HISTORY_PATH)
    except OSError:
        # best-effort annotation: a read-only checkout or full disk must
        # never cost the driver its one-line JSON contract
        pass


def _supersede(lane: dict, entry: dict, reason: str) -> None:
    """Retire a history row: stale-marked and appended to the lane's
    bounded `superseded` list (ROADMAP: mark, don't delete)."""
    old = dict(entry)
    old["stale"] = True
    old["stale_reason"] = reason
    lane.setdefault("superseded", []).append(old)
    del lane["superseded"][:-8]  # bounded provenance


def emit(result: dict) -> None:
    """Print the one-line JSON contract; mirror to --json-out if set (the
    --fill orchestrator reads it back from the subprocess)."""
    global LAST_RESULT
    _regression_guard(result)
    LAST_RESULT = result
    line = json.dumps(result)
    print(line)
    if JSON_OUT:
        with open(JSON_OUT, "w") as f:
            f.write(line + "\n")


def _load_rows() -> dict:
    try:
        with open(ROWS_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _store_row(config: str, result: dict) -> None:
    """Checkpoint a completed config's result the moment it finishes —
    tunnel flaps must never cost an already-captured row."""
    rows = _load_rows()
    rows[config] = result
    tmp = ROWS_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rows, f, indent=1, sort_keys=True)
    os.replace(tmp, ROWS_PATH)


def build_world(n_keys=1024, n_existing=65536, n_batch=512, seed=42,
                zipf_alpha=0.99):
    from accord_tpu.local.cfk import CommandsForKey, InternalStatus
    from accord_tpu.primitives.keys import Key
    from accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind
    from accord_tpu.utils.random_source import RandomSource

    rng = RandomSource(seed)
    keys = [Key(i) for i in range(n_keys)]
    cfks = {k: CommandsForKey(k) for k in keys}
    kinds = [TxnKind.READ, TxnKind.WRITE]
    statuses = [InternalStatus.PREACCEPTED, InternalStatus.ACCEPTED,
                InternalStatus.COMMITTED, InternalStatus.STABLE,
                InternalStatus.APPLIED]

    # bounded-Zipf key picker (same scheme as the burn harness)
    weights = 1.0 / np.arange(1, n_keys + 1) ** zipf_alpha
    cdf = np.cumsum(weights / weights.sum())

    def pick_key():
        return keys[int(np.searchsorted(cdf, rng.next_float()))]

    hlc = 1000
    for _ in range(n_existing):
        hlc += 1 + rng.next_int(2)
        tid = TxnId.create(1, hlc, rng.pick(kinds), Domain.KEY,
                           rng.next_int(8))
        for k in {pick_key() for _ in range(1 + rng.next_int(3))}:
            cfks[k].update(tid, rng.pick(statuses), None)
    batch = []
    for _ in range(n_batch):
        hlc += 1 + rng.next_int(2)
        tid = TxnId.create(1, hlc, rng.pick(kinds), Domain.KEY,
                           rng.next_int(8))
        batch.append((tid, sorted({pick_key() for _ in range(1 + rng.next_int(4))})))
    return list(cfks.values()), batch


def scalar_edges_per_sec(cfks, batch):
    by_key = {c.key: c for c in cfks}
    edges = 0

    def count(_):
        nonlocal edges
        edges += 1

    t0 = time.perf_counter()
    for tid, keyset in batch:
        for k in keyset:
            by_key[k].map_reduce_active(tid, tid.kind.witnesses(), count)
    dt = time.perf_counter() - t0
    return edges / dt, edges


def bench_scalar(n_keys=256, n_existing=8192, n_batch=128):
    """Fast host-only config (never imports jax): the scalar active-scan
    hot loop with a per-"kernel" profile, giving `--guard` a lane that can
    run anywhere in seconds.  The profiled section is the same
    CommandsForKey.map_reduce_active walk the device tier displaces."""
    from accord_tpu.obs.profiler import Profiler
    from accord_tpu.obs.registry import Registry

    cfks, batch = build_world(n_keys=n_keys, n_existing=n_existing,
                              n_batch=n_batch)
    by_key = {c.key: c for c in cfks}
    prof = Profiler(Registry(), sample_n=1)
    edges = 0

    def count(_):
        nonlocal edges
        edges += 1

    t0 = time.perf_counter()
    for tid, keyset in batch:
        prof.window_begin(None)
        t = prof.begin()
        for k in keyset:
            by_key[k].map_reduce_active(tid, tid.kind.witnesses(), count)
        prof.lap(t, "scalar_scan")
        prof.window_end()
    dt = max(time.perf_counter() - t0, 1e-9)
    emit({
        "metric": "scalar_edges_resolved_per_sec",
        "value": round(edges / dt, 1),
        "unit": "edges/s",
        "edges": edges,
        "txns": n_batch,
        "profile": prof.summary(),
    })


def _profile_device_kernels(args, reps: int = 24) -> dict:
    """Per-kernel fenced wall profile for the device headline row: each
    kernel timed individually, every lap ended by a host pull (the fence),
    with the retrace ledger keyed by the argument shapes — the summary
    bench records into the emitted row and BENCH_HISTORY (`--guard` input)."""
    import jax.numpy as jnp

    from accord_tpu.obs.profiler import Profiler
    from accord_tpu.obs.registry import Registry
    from accord_tpu.ops.deps_kernel import batched_active_deps, in_batch_graph
    from accord_tpu.ops.wavefront import execution_waves

    (er, eer, ek, es, ekd, tr, twm, tkd, touches) = args
    prof = Profiler(Registry(), sample_n=1)
    # warm-up compiles outside the timed laps (the ledger still counts the
    # shape buckets — one compile per kernel at this shape)
    prof.note_retrace("deps_kernel", (er.shape, touches.shape))
    prof.note_retrace("in_batch_graph", (touches.shape,))
    prof.note_retrace("wavefront", (touches.shape[0],))
    np.asarray(batched_active_deps(er, eer, ek, es, ekd, tr, twm,
                                   touches)[1])
    g = in_batch_graph(tr, twm, tkd, touches)
    np.asarray(execution_waves(g))
    for _ in range(reps):
        prof.window_begin(None)
        t = prof.begin()
        out = batched_active_deps(er, eer, ek, es, ekd, tr, twm, touches)
        np.asarray(out[1])                       # host pull == fence
        t = prof.lap(t, "deps_kernel")
        g = in_batch_graph(tr, twm, tkd, touches)
        g_host = np.asarray(g)
        t = prof.lap(t, "in_batch_graph")
        np.asarray(execution_waves(jnp.asarray(g_host)))
        prof.lap(t, "wavefront")
        prof.window_end()
    return prof.summary()


def _xla_window_body(entry_rank, entry_eat_rank, entry_key, entry_status,
                     entry_kind, txn_rank, txn_witness_mask, txn_kind,
                     touches):
    """resolve_step's pipeline with the explicitly-XLA wavefront, safe to
    wrap in lax.scan (the platform's pallas lowering rejects pallas inside
    scan). Returns the three summary scalars the bench aggregates."""
    import jax.numpy as jnp

    from accord_tpu.ops.deps_kernel import batched_active_deps, in_batch_graph
    from accord_tpu.ops.wavefront import execution_waves

    _, dep_count = batched_active_deps(
        entry_rank, entry_eat_rank, entry_key, entry_status, entry_kind,
        txn_rank, txn_witness_mask, touches)
    dep_bb = in_batch_graph(txn_rank, txn_witness_mask, txn_kind, touches)
    waves = execution_waves(dep_bb)
    return (dep_count.sum(dtype=jnp.int32), dep_bb.sum(dtype=jnp.int32),
            waves.max())


def _default_reps_fn(reps: int):
    """One jitted call = `reps` full resolve passes, iteration-skewed by
    rolling the txn batch (results are permutation-invariant aggregates, so
    every rep reproduces the same three scalars while denying the compiler
    any loop-invariant hoisting)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(er, eer, ek, es, ekd, tr, twm, tkd, touches):
        def body(carry, i):
            ys = _xla_window_body(
                er, eer, ek, es, ekd,
                jnp.roll(tr, i), jnp.roll(twm, i), jnp.roll(tkd, i),
                jnp.roll(touches, i, axis=0))
            return carry, ys

        _, ys = jax.lax.scan(body, 0, jnp.arange(reps))
        return ys

    return run


def bench_default():
    import jax

    from accord_tpu.ops.encode import BatchEncoder
    from accord_tpu.ops.sharded import resolve_step

    cfks, batch = build_world()
    enc = BatchEncoder(cfks, batch)
    s, b = enc.state, enc.dbatch
    args = [jax.device_put(x) for x in
            (s.entry_rank, s.entry_eat_rank, s.entry_key, s.entry_status,
             s.entry_kind, b.txn_rank, b.txn_witness_mask, b.txn_kind,
             b.touches)]

    # correctness reference: one resolve_step call (the protocol-path
    # pipeline, pallas wave on real TPU), pulled for the edge count
    out = resolve_step(*args)
    edges = int(np.asarray(out[1]).sum())

    # HONEST timing: block_until_ready is not a reliable sync on the
    # tunneled platform (measured returning early), a device->host pull
    # costs a full tunnel RTT, and after the first pull every dispatch
    # degrades to synchronous (each paying RTT). So fold the iterations
    # INTO one jitted computation (lax.scan, iteration-skewed by rolling
    # the batch so nothing is loop-invariant) and difference a 10-rep call
    # against a 110-rep call — each is ONE dispatch + ONE pull, so RTT
    # cancels exactly, leaving 100 reps of pure device time.
    small_n, large_n = 10, 110
    run_small = _default_reps_fn(small_n)
    run_large = _default_reps_fn(large_n)
    # warm-up must end with host PULLS (block_until_ready is the unreliable
    # sync this methodology exists to avoid) so the timed calls below run
    # in the same post-transfer dispatch regime
    np.asarray(run_small(*args))
    np.asarray(run_large(*args))

    def timed(fn):
        t0 = time.perf_counter()
        ys = fn(*args)
        host = np.asarray(ys[0]), np.asarray(ys[1]), np.asarray(ys[2])
        return time.perf_counter() - t0, host

    t_small, h_small = timed(run_small)
    t_large, h_large = timed(run_large)
    for h in (h_small, h_large):
        assert (h[0] == h[0][0]).all() and int(h[0][0]) == edges
    dt = max(t_large - t_small, 1e-9)
    iters = large_n - small_n

    device_eps = edges * iters / dt

    scalar_eps, scalar_edges = scalar_edges_per_sec(cfks, batch)
    assert scalar_edges == edges, (
        f"device/scalar edge mismatch: {edges} vs {scalar_edges}")

    result = {
        "metric": "conflict_graph_edges_resolved_per_sec",
        "value": round(device_eps, 1),
        "unit": "edges/s",
        "vs_baseline": round(device_eps / scalar_eps, 2),
        "platform": PLATFORM,
        # per-kernel p50/p99 + retrace counts (obs/profiler.py) — the
        # `--guard` regression gate's per-kernel input
        "profile": _profile_device_kernels(args),
    }
    if PLATFORM.startswith("cpu"):
        # tunnel dead at capture time: point at the checkpointed on-chip
        # capture (BENCH_DEVICE_ROWS.json, written by --fill during a live
        # window) so the artifact still carries the chip evidence
        row = _load_rows().get("default")
        if row and row.get("platform", "").startswith("axon"):
            result["last_onchip"] = {
                "value": row["value"], "vs_baseline": row.get("vs_baseline"),
                "platform": row["platform"],
                "captured_unix": row.get("captured_unix")}
    emit(result)


# ------------------------------------------------------- shared helpers ----

def _witness_mask_for(kind):
    from accord_tpu.ops.encode import witness_mask
    return witness_mask(kind)


# ----------------------------------------------------------- rangestress ----

def _range_reps_fn(reps: int):
    """One jitted call = `reps` passes of the full chunked stab workload
    (roll-skewed iterations; totals are permutation-invariant). Returns the
    per-rep total intersect count [reps]."""
    import jax
    import jax.numpy as jnp

    from accord_tpu.ops.range_kernel import range_stab_counts

    @jax.jit
    def run(s, e, qs_stack, qe_stack):
        def rep(carry, i):
            qs = jnp.roll(jnp.roll(qs_stack, i, axis=0), i, axis=1)
            qe = jnp.roll(jnp.roll(qe_stack, i, axis=0), i, axis=1)

            def body(c, xs):
                a, b = xs
                return c, range_stab_counts(s, e, a, b).sum(dtype=jnp.int32)

            _, sums = jax.lax.scan(body, 0, (qs, qe))
            return carry, sums.sum(dtype=jnp.int32)

        _, ys = jax.lax.scan(rep, 0, jnp.arange(reps))
        return ys

    return run


def bench_rangestress(n_ranges=1_000_000, n_txns=10_000, seed=42,
                      universe=1_000_000_000):
    """BASELINE row: RangeDeps stress — 10k range-scan txns stabbing 1M
    intervals. Device tier: one fused [Q, N] compare-reduce per query chunk
    (ops/range_kernel.py), the TPU-native replacement for the reference's
    CINTIA checkpoint search (RangeDeps.java + CheckpointIntervalArray). A
    numpy re-derivation validates counts on a query sample."""
    import jax

    from accord_tpu.ops.range_kernel import stab_counts_chunked

    rng = np.random.default_rng(seed)
    starts = rng.integers(0, universe - 1_000_000, n_ranges)
    ends = starts + rng.integers(1, 1_000_000, n_ranges)
    q_starts = rng.integers(0, universe - 2_000_000, n_txns)
    q_ends = q_starts + rng.integers(1000, 2_000_000, n_txns)

    # move intervals to device once
    dev_starts = jax.device_put(starts.astype(np.int32))
    dev_ends = jax.device_put(ends.astype(np.int32))

    # correctness first (untimed): per-query counts + host sample check
    counts = stab_counts_chunked(dev_starts, dev_ends, q_starts, q_ends)
    per_query = np.concatenate([np.asarray(c) for c in counts])[:n_txns]
    edges = int(per_query.sum())
    for qi in rng.integers(0, n_txns, 5):
        want = int(np.count_nonzero((starts < q_ends[qi])
                                    & (ends > q_starts[qi])))
        assert per_query[qi] == want, (qi, per_query[qi], want)

    # HONEST timing (see module docstring): queries stacked [C, chunk] with
    # zero-padding (degenerate [0, 0) queries hit nothing), reps folded
    # inside the jit with roll-skewed iterations, one-rep vs three-rep
    # differencing in the same post-pull dispatch regime.
    chunk = 256
    pad = (-len(q_starts)) % chunk
    qs_stack = np.concatenate([q_starts, np.zeros(pad, np.int64)]) \
        .astype(np.int32).reshape(-1, chunk)
    qe_stack = np.concatenate([q_ends, np.zeros(pad, np.int64)]) \
        .astype(np.int32).reshape(-1, chunk)
    dev_qs, dev_qe = jax.device_put(qs_stack), jax.device_put(qe_stack)
    fn1, fn3 = _range_reps_fn(1), _range_reps_fn(3)
    for fn in (fn1, fn3):
        np.asarray(fn(dev_starts, dev_ends, dev_qs, dev_qe))

    def timed(fn):
        t0 = time.perf_counter()
        ys = np.asarray(fn(dev_starts, dev_ends, dev_qs, dev_qe))
        return time.perf_counter() - t0, ys

    t1, y1 = timed(fn1)
    t3, y3 = timed(fn3)
    assert (y3 == y3[0]).all() and int(y1[0]) == edges == int(y3[0])
    dt = max((t3 - t1) / 2, 1e-9)

    emit(dict({
        "metric": "rangestress_edges_resolved_per_sec",
        "value": round(edges / dt, 1),
        "unit": "edges/s",
        "platform": PLATFORM,
        "edges": edges,
        "txns": n_txns,
        "txns_per_sec": round(n_txns / dt, 1),
        "intervals": n_ranges,
        "device_seconds": round(dt, 4),
    }))


# ------------------------------------------------------------ maelstrom ----

def bench_maelstrom(nodes=3, keys=100, n_ops=400, single_key=True,
                    seed=7):
    """BASELINE rows 1-2: black-box throughput of the HOST protocol engine —
    real OS-process nodes speaking the Maelstrom JSON wire format, real wall
    clock, strict serializability verified post-run. Runs CPU-only (the host
    tier never touches the chip)."""
    from accord_tpu.host.runner import MaelstromRunner

    r = MaelstromRunner(n_nodes=nodes, seed=seed)
    try:
        r.init_all()
        t0 = time.perf_counter()
        stats = r.run_workload(n_ops=n_ops, n_keys=keys, pipeline=16,
                               single_key=single_key)
        dt = time.perf_counter() - t0
        checked = r.check_strict_serializability(keys)  # raises on violation
    finally:
        r.close()
    assert checked > 0.9 * n_ops, (checked, stats)
    assert stats["acked"] > 0.9 * n_ops, stats
    shape = "lin-kv single-key" if single_key else "txn-rw multi-key RMW"
    emit(dict({
        "metric": "maelstrom_host_txn_per_sec",
        "value": round(stats["acked"] / dt, 1),  # only verified-acked txns
        "unit": "txn/s",
        "workload": shape,
        "nodes": nodes,
        "keys": keys,
        "ops": stats["completed"],
        "acked": stats["acked"],
        "wall_seconds": round(dt, 2),
        "verified": "strict-serializable",
    }))


def bench_tcp(nodes=3, keys=100, n_ops=400, seed=7, pipeline=16,
              metric="tcp_host_txn_per_sec", extra_fields=None):
    """BASELINE row: black-box throughput over the REAL-SOCKET transport —
    one OS process (one GIL) per node, inter-node traffic on direct TCP
    connections (no relay bus, unlike the Maelstrom harness where every
    message funnels through the single-threaded stdio router), strict
    serializability verified post-run.  CPU-only.

    The `pipeline` arg is the CLIENT's in-flight depth; with
    ACCORD_PIPELINE=1 in the environment the node processes additionally
    run the continuous micro-batching ingest layer (--config pipeline)."""
    import random

    from accord_tpu.host.tcp import TcpClusterClient
    from accord_tpu.sim.verify import (Observation,
                                       StrictSerializabilityVerifier)

    # guard tests shrink the lane (ACCORD_BENCH_TCP_OPS/_KEYS); the
    # protocol-CPU waterfall samples 1-in-2 dispatches in every node
    # process so the row always carries the per-verb "cpu" section
    # (overridable; the hooks are a handful of clock reads per sampled
    # dispatch vs ~100us+ applies, so the lane's numbers are unaffected)
    n_ops = int(os.environ.get("ACCORD_BENCH_TCP_OPS", n_ops))
    keys = int(os.environ.get("ACCORD_BENCH_TCP_KEYS", keys))
    os.environ.setdefault("ACCORD_CPU_PROFILE", "2")

    rng = random.Random(seed)
    c = TcpClusterClient(n_nodes=nodes)
    obs = []
    try:
        state = {"value": 0, "submitted": 0}
        pending = {}

        def submit_one():
            to = 1 + rng.randrange(nodes)
            k = rng.randrange(keys)
            reads, appends = [k], {}
            if rng.random() < 0.7:
                state["value"] += 1
                appends[k] = state["value"]
            if rng.random() < 0.3:
                k2 = rng.randrange(keys)
                if k2 not in appends:
                    state["value"] += 1
                    appends[k2] = state["value"]
            req = state["submitted"]
            state["submitted"] += 1
            pending[req] = (time.monotonic(), dict(appends), to)
            c.submit(to, reads, appends, req)

        t0 = time.perf_counter()
        for _ in range(min(pipeline, n_ops)):
            submit_one()
        acked = completed = 0
        deadline = time.monotonic() + 300
        while completed < n_ops and time.monotonic() < deadline:
            frame = c.recv(5.0)
            if frame is None:
                continue
            body = frame.get("body", {})
            if body.get("type") != "submit_reply":
                continue
            completed += 1
            start, appends, to = pending.pop(body["req"])
            if body["ok"]:
                acked += 1
                obs.append(Observation(
                    f"txn{body['req']}@n{to}",
                    {int(t): tuple(v) for t, v in body["reads"].items()},
                    appends, int(start * 1e6),
                    int(time.monotonic() * 1e6)))
            if state["submitted"] < n_ops:
                submit_one()
        dt = time.perf_counter() - t0

        # final histories (not timed): chunked read-only txns
        final = {}
        req = 10 ** 9
        for lo in range(0, keys, 20):
            chunk = list(range(lo, min(lo + 20, keys)))
            # read-only txns are idempotent: retry a timed-out round (a
            # node may still be paying first-jit costs under
            # ACCORD_TCP_DEVICE_STORE)
            for attempt in range(4):
                c.submit(1, chunk, {}, req)
                body = None
                while True:
                    frame = c.recv(30.0)
                    assert frame is not None, "final read timed out"
                    b = frame.get("body", {})
                    if b.get("type") == "submit_reply" \
                            and b.get("req") == req:
                        body = b
                        break
                req += 1
                if body["ok"]:
                    for t, v in body["reads"].items():
                        final[int(t)] = tuple(v)
                    break
                assert attempt < 3, body
        from accord_tpu.sim.verify_replay import full_verifier
        verifier = full_verifier(witness_replay=False)
        for o in obs:
            verifier.observe(o)
        verifier.verify(final)  # raises on any anomaly

        # obs snapshot from every node process (JSON over the frame
        # transport; the Prometheus endpoint is the ACCORD_METRICS_PORT
        # alternative) — recorded in the BENCH row: fast-path ratio,
        # per-phase latency histograms, device flush-window counts
        from accord_tpu.obs.report import merge_node_snapshots
        snaps = [c.fetch_metrics(i) for i in range(1, nodes + 1)]
        merged = merge_node_snapshots([s for s in snaps if s])
        obs_summary = merged["summary"] if merged["nodes"] else None
    finally:
        c.close()
    assert acked > 0.9 * n_ops, (acked, completed)
    cpu_summary = None
    if obs_summary is not None:
        # the protocol-CPU waterfall is its own top-level row key (the
        # `--guard` per-verb gate's input), not buried in obs
        cpu_summary = obs_summary.pop("cpu", None)
    result = {
        "metric": metric,
        "value": round(acked / dt, 1),
        "unit": "txn/s",
        "workload": "lin-kv read+append mix, direct-socket cluster",
        "nodes": nodes,
        "keys": keys,
        "ops": completed,
        "acked": acked,
        "client_inflight": pipeline,
        "wall_seconds": round(dt, 2),
        "verified": "strict-serializable",
    }
    if obs_summary is not None:
        result["obs"] = obs_summary
    if cpu_summary is not None and cpu_summary.get("sampled"):
        result["cpu"] = cpu_summary
    if extra_fields:
        result.update(extra_fields)
    emit(result)


def bench_journal(n_append=20000, inflight=256, fsync_window_us=2000,
                  sync_ops=640):
    """Satellite of the durable-WAL tentpole (accord_tpu/journal/): group
    commit vs fsync-per-append at EQUAL durability.  Both lanes run the
    host's actual ack discipline — append, then release the ack from an
    `on_durable` callback once the COVERING FSYNC has landed (what
    DurableAckSink does to replies; no thread blocks per txn) — with a
    bounded in-flight window like a loaded node's dispatch loop.  The only
    difference between the lanes is the fsync policy: a deadline/batch/
    idle-bounded group-commit window (one fsync covers a window's worth of
    appends) vs the synchronous mode's fsync per append.  The emitted
    ratio is therefore exactly the cost of NOT batching durability."""
    import tempfile
    import threading

    from accord_tpu.journal.wal import JournalConfig, WriteAheadLog
    from accord_tpu.obs.report import summarize

    def sample_request():
        # a real journaled verb with small fixed encode cost (~220 bytes,
        # ~14us): both lanes pay encoding identically, so a bulky payload
        # would only dilute the fsync-discipline difference this lane
        # exists to measure (encode throughput has its own lanes)
        from accord_tpu.messages.commit import CommitInvalidate
        from accord_tpu.primitives.keys import Route, RoutingKey, RoutingKeys
        from accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind
        tid = TxnId.create(1, 12345, TxnKind.WRITE, Domain.KEY, 1)
        return CommitInvalidate(
            tid, Route.of_keys(RoutingKey(11), RoutingKeys.of(11, 42)))

    msg = sample_request()

    def run_mode(window_us: int, total: int) -> tuple:
        d = tempfile.mkdtemp(prefix="bench-wal-")
        cfg = JournalConfig(d, fsync_window_us=window_us,
                            segment_bytes=64 << 20, snapshot_segments=0)
        wal = WriteAheadLog(d, config=cfg, retain=False)
        window = threading.BoundedSemaphore(inflight)
        acked = threading.Semaphore(0)
        t0 = time.perf_counter()
        for _ in range(total):
            window.acquire()
            seq = wal.append(msg)
            wal.on_durable(seq, lambda: (window.release(),
                                         acked.release()))
        for _ in range(total):  # every ack observed before the clock stops
            acked.acquire()
        dt = max(time.perf_counter() - t0, 1e-9)
        assert wal.durable_seq >= total
        snap = wal.registry.snapshot()
        wal.close()
        return total / dt, snap

    group_tps, group_snap = run_mode(fsync_window_us, n_append)
    sync_tps, _sync_snap = run_mode(0, sync_ops)
    journal_obs = summarize(group_snap)["journal"]
    emit({
        "metric": "journal_group_commit_append_per_sec",
        "value": round(group_tps, 1),
        "unit": "append/s",
        "workload": f"durable-acked (on_durable callbacks, {inflight} "
                    f"in flight) wire-encoded requests",
        "appends": n_append,
        "fsync_window_us": fsync_window_us,
        "fsync_per_append_per_sec": round(sync_tps, 1),
        "group_vs_fsync_ratio": round(group_tps / max(sync_tps, 1e-9), 1),
        "fsyncs_group": journal_obs["fsyncs"],
        "batch_mean": journal_obs["group_commit_batch"]["mean"],
        "obs": {"journal": journal_obs},
    })


def bench_pipeline(nodes=3, keys=100, n_ops=400, seed=7):
    """Satellite of the ingest-pipeline tentpole: the SAME tcp workload and
    differenced wall-clock discipline, with ACCORD_PIPELINE=1 in every node
    process — client submissions coalesce into micro-batches (one
    MultiPreAccept envelope per replica per batch; fused device windows
    when ACCORD_TCP_DEVICE_STORE=1).  Client in-flight depth is raised to
    64 so admission pressure actually forms batches at max_batch=8.
    History lanes: 'pipeline' (scalar stores) / 'pipeline+device', vs the
    per-txn 'tcp' / 'tcp+device' lanes."""
    os.environ["ACCORD_PIPELINE"] = "1"
    os.environ.setdefault("ACCORD_PIPELINE_MAX_BATCH", "8")
    os.environ.setdefault("ACCORD_PIPELINE_MAX_WAIT_US", "2000")
    device = os.environ.get("ACCORD_TCP_DEVICE_STORE", "") == "1"
    per_txn_lane = "tcp+device" if device else "tcp"
    extra = {
        "max_batch": int(os.environ["ACCORD_PIPELINE_MAX_BATCH"]),
        "max_wait_us": int(os.environ["ACCORD_PIPELINE_MAX_WAIT_US"]),
        "device_store": device,
    }
    try:
        with open(HISTORY_PATH) as f:
            prev = json.load(f).get(per_txn_lane, {}).get("host")
        if prev and prev.get("value"):
            extra["per_txn_baseline"] = {"config": per_txn_lane,
                                         "value": prev["value"]}
    except (OSError, ValueError):
        pass
    bench_tcp(nodes=nodes, keys=keys, n_ops=n_ops, seed=seed, pipeline=64,
              metric="pipeline_tcp_host_txn_per_sec", extra_fields=extra)


# ----------------------------------------------------------- multicore -----

def bench_multicore(n_ops=200, keys=50, shards_list=(1, 2, 4),
                    depth=8, seed=7):
    """Tentpole lane of the per-shard worker runtime (accord_tpu/shard/):
    ONE node whose command stores run as N worker PROCESSES (one selector
    event loop, one store, one WAL band, one GIL each), driven by one
    closed-loop client at fixed inflight depth.  Aggregate throughput
    rising as ACCORD_SHARDS grows IS the multi-core scaling story — the
    old lane ran N independent rf=1 clusters, which measured process
    isolation, not intra-node sharding.  shards=1 is the in-loop tier
    (ACCORD_SHARDS unset — byte-for-byte the pre-shard wiring), so the
    first row doubles as the non-regression anchor vs the tcp lane.

    `cpus_available` documents the ceiling this box exposes: with fewer
    cores than workers the table can only measure pipe + scheduling
    overhead, not scaling — the row records both the per-count table and
    the 1->max aggregate ratio so a ≥4-core box shows the real curve."""
    import random

    from accord_tpu.host.tcp import TcpClusterClient

    # the per-verb CPU waterfall rides this lane's row too (see bench_tcp)
    os.environ.setdefault("ACCORD_CPU_PROFILE", "2")

    try:
        cpus = sorted(os.sched_getaffinity(0))
    except AttributeError:  # non-linux
        cpus = [0]

    def drive(n_shards: int):
        if n_shards >= 2:
            os.environ["ACCORD_SHARDS"] = str(n_shards)
        else:
            os.environ.pop("ACCORD_SHARDS", None)
        rng = random.Random(seed + n_shards)
        c = TcpClusterClient(n_nodes=1)
        try:
            t0 = time.perf_counter()
            sub = done = acked = 0

            def sub_one():
                nonlocal sub
                k = rng.randrange(keys)
                c.submit(1, [k], {k: sub + 1}, req=sub)
                sub += 1

            for _ in range(min(depth, n_ops)):
                sub_one()
            while done < n_ops:
                frame = c.recv(30.0)
                body = (frame or {}).get("body", {})
                if body.get("type") != "submit_reply":
                    continue
                done += 1
                if body.get("ok"):
                    acked += 1
                if sub < n_ops:
                    sub_one()
            dt = time.perf_counter() - t0
            from accord_tpu.obs.report import merge_node_snapshots
            snap = c.fetch_metrics(1)
            merged = merge_node_snapshots([snap] if snap else [])
            return (acked, dt,
                    merged["summary"] if merged["nodes"] else None)
        finally:
            c.close()
            os.environ.pop("ACCORD_SHARDS", None)

    table = {}
    obs_summary = None
    for n_shards in shards_list:
        acked, wall, summary = drive(n_shards)
        assert acked > 0.9 * n_ops, (n_shards, acked)
        table[str(n_shards)] = {
            "aggregate_txn_per_s": round(acked / wall, 1),
            "acked": acked,
            "wall_seconds": round(wall, 2),
            "tier": "workers" if n_shards >= 2 else "in-loop",
        }
        if obs_summary is None:
            obs_summary = summary
    first = table[str(shards_list[0])]["aggregate_txn_per_s"]
    last = table[str(shards_list[-1])]["aggregate_txn_per_s"]
    # headline = best point on the sweep: on a multi-core box that is the
    # max-worker row; on a 1-core box it degenerates to the in-loop tier,
    # which keeps the row comparable to (and non-regressing vs) the tcp
    # lane instead of charging pipe overhead the box can't amortise
    best = max(table, key=lambda k: table[k]["aggregate_txn_per_s"])
    result = {
        "metric": "multicore_aggregate_txn_per_sec",
        "value": table[best]["aggregate_txn_per_s"],
        "best_shards": int(best),
        "unit": "txn/s",
        "workload": f"one node, ACCORD_SHARDS swept {list(shards_list)} "
                    f"(shard worker processes), closed-loop client "
                    f"depth {depth}",
        "shards": list(shards_list),
        "cpus_available": len(cpus),
        "per_shards": table,
        "scaling_first_to_last": round(last / first, 2) if first else None,
        "ops": n_ops,
        "client_inflight": depth,
    }
    if obs_summary is not None:
        cpu_summary = obs_summary.pop("cpu", None)
        result["obs"] = obs_summary
        if cpu_summary is not None and cpu_summary.get("sampled"):
            result["cpu"] = cpu_summary
    emit(result)


# ---------------------------------------------------------------- tpcc -----

def _tpcc_resolve_core():
    import jax.numpy as jnp

    from accord_tpu.ops.deps_kernel import conflict_edges
    from accord_tpu.ops.wavefront import execution_waves

    P = 11

    def resolve(prev_write_rank, txn_rank, txn_keys):
        """One window of the replay against watermark-pruned state.

        With cleanup keeping only each key's latest committed write (the
        RedundantBefore contract, local/cleanup.py), a new-order txn's deps
        are (a) that writer for each touched key — never elidable, it IS the
        elision bound — and (b) in-window conflicts, which are uncommitted
        and so never elide anything. No [B, E] tile exists at all."""
        valid = txn_keys >= 0
        pw = jnp.where(valid, prev_write_rank[jnp.clip(txn_keys, 0, None)],
                       -1)
        dep_count = (pw >= 0).sum(axis=1, dtype=jnp.int32)       # [B]

        shared = jnp.zeros((txn_rank.shape[0],) * 2, bool)
        for i in range(P):                                        # unrolled:
            for j in range(P):                                    # 121 [B,B]
                shared |= ((txn_keys[:, i, None] == txn_keys[None, :, j])
                           & valid[:, i, None] & valid[None, :, j])
        wit = jnp.full_like(txn_rank, _witness_mask_for_write())
        kind = jnp.ones_like(txn_rank)
        dep_bb = conflict_edges(shared, txn_rank, wit, kind)
        waves = execution_waves(dep_bb)
        return dep_count, dep_bb.sum(dtype=jnp.int32), waves.max()

    return resolve


def _tpcc_stack_fn(use_pallas: bool, reps: int):
    """Resolve a whole STACK of same-shape windows in ONE dispatch, `reps`
    times (for the differencing timer — see bench_default's note): the
    windows are independent given the host-precomputed prev-writer state.
    On TPU the window body is the fused VMEM-resident Pallas kernel
    (pallas_kernels.keyset_windows_pallas; reps folded into its grid, since
    pallas inside lax.scan fails to lower here) — the XLA fallback
    materialises all P*P [B,B] compare intermediates in HBM, which alone is
    ~3.5 ms per 2048-txn window. Returns [reps, 3] i32 (cross edges,
    in-window edges, max wave), identical rows."""
    import jax
    import jax.numpy as jnp

    if use_pallas:
        from accord_tpu.ops.pallas_kernels import keyset_windows_pallas

        @jax.jit
        def resolve_stack(prevs, ranks, keyss):
            w = keyss.shape[0]

            def rep(carry, i):
                tk = jnp.roll(jnp.roll(keyss, i, axis=0), i, axis=1)
                pv = jnp.roll(prevs, i, axis=0)
                valid = tk >= 0
                pw = jnp.where(
                    valid,
                    pv[jnp.arange(w)[:, None, None], jnp.clip(tk, 0, None)],
                    -1)
                # int32 is ample: <=22,528 cross edges/window, ~7.5M total
                return carry, (pw >= 0).sum(dtype=jnp.int32)

            _, cross_r = jax.lax.scan(rep, 0, jnp.arange(reps))
            in_w, wave_w = keyset_windows_pallas(keyss, ranks, reps=reps)
            in_tot = in_w.sum(dtype=jnp.int32)
            wave_m = wave_w.max()
            return jnp.stack(
                [cross_r, jnp.full((reps,), in_tot), jnp.full((reps,), wave_m)],
                axis=1)

        return resolve_stack

    @jax.jit
    def resolve_stack(prevs, ranks, keyss):
        def rep(carry, i):
            pv = jnp.roll(prevs, i, axis=0)
            tr = jnp.roll(jnp.roll(ranks, i, axis=0), i, axis=1)
            tk = jnp.roll(jnp.roll(keyss, i, axis=0), i, axis=1)

            def body(c, xs):
                prev, trw, tkw = xs
                dep_count, in_edges, max_wave = _tpcc_resolve_core()(
                    prev, trw, tkw)
                return c, (dep_count.sum(dtype=jnp.int32), in_edges, max_wave)

            _, (cross_w, in_w, wave_w) = jax.lax.scan(body, 0, (pv, tr, tk))
            return carry, jnp.stack([cross_w.sum(dtype=jnp.int32),
                                     in_w.sum(dtype=jnp.int32),
                                     wave_w.max()])

        _, ys = jax.lax.scan(rep, 0, jnp.arange(reps))
        return ys                                              # [reps, 3]

    return resolve_stack


def _witness_mask_for_write():
    from accord_tpu.primitives.timestamp import TxnKind
    return _witness_mask_for(TxnKind.WRITE)


def bench_tpcc(n_txns=1_000_000, warehouses=64, window=2048, seed=42):
    """BASELINE north star: TPC-C new-order replay, 64 warehouses, 1M-txn
    conflict graph. Each txn hits its district O_ID counter (the classic
    contention point) plus 10 stock keys (1% remote warehouse). Resolves the
    full graph window-by-window against pruned state; reports device resolve
    time (target: <50 ms on v5e-8 — measured here on ONE chip)."""
    import jax

    rng = np.random.default_rng(seed)
    P = 11
    t_prep = time.perf_counter()
    w = rng.integers(0, warehouses, n_txns)
    d = rng.integers(0, 10, n_txns)
    district = (w * 10 + d).astype(np.int64)                    # keys 0..639
    items = rng.integers(0, 100_000, (n_txns, 10))
    remote = rng.random((n_txns, 10)) < 0.01
    s_w = np.where(remote, rng.integers(0, warehouses, (n_txns, 10)),
                   w[:, None])
    stock = 1000 + (s_w * 100_000 + items).astype(np.int64)
    keys = np.concatenate([district[:, None], stock], axis=1)   # [N, 11]

    last_writer: dict = {}
    host_windows = []
    for w0 in range(0, n_txns, window):
        kwin = keys[w0:w0 + window]
        B = kwin.shape[0]
        uniq = np.unique(kwin)
        kmap = {int(k): i for i, k in enumerate(uniq)}
        K = 1024
        while K < len(uniq):
            K *= 2
        prev = np.full(K, -1, np.int32)
        for k, i in kmap.items():
            prev[i] = last_writer.get(k, -1)
        txn_keys = np.full((window, P), -1, np.int32)
        for b in range(B):
            row = sorted({kmap[int(k)] for k in kwin[b]})
            txn_keys[b, :len(row)] = row
        txn_rank = np.full(window, -1, np.int32)
        txn_rank[:B] = np.arange(w0, w0 + B, dtype=np.int32)
        for b in range(B):                                      # state advance
            for k in kwin[b]:
                last_writer[int(k)] = w0 + b
        host_windows.append((prev, txn_rank, txn_keys))

    # stack same-K windows so each bucket is ONE device dispatch (a lax.scan
    # over the stack) instead of one dispatch per window
    buckets: dict = {}
    for wargs in host_windows:
        buckets.setdefault(wargs[0].shape[0], []).append(wargs)
    want_pallas = PLATFORM not in ("cpu", "unprobed") \
        and not PLATFORM.startswith("cpu-fallback")
    dev_stacks = [tuple(jax.device_put(np.stack([w[i] for w in ws]))
                        for i in range(3))
                  for ws in buckets.values()]
    prep_s = time.perf_counter() - t_prep

    # compile both rep counts for every K bucket; the warm-up ends with a
    # host PULL so both timed passes below run in the same (post-transfer,
    # synchronous-dispatch) regime — otherwise the one-rep pass would run
    # async and the three-rep pass sync, and their difference would carry
    # one uncancelled RTT per bucket. If the Pallas path fails to lower on
    # this platform, fall back to pure XLA rather than crash.
    def compile_fns(pallas: bool):
        f1, f3 = _tpcc_stack_fn(pallas, 1), _tpcc_stack_fn(pallas, 3)
        for args in dev_stacks:
            np.asarray(f1(*args))
            np.asarray(f3(*args))
        return f1, f3

    kernel_path = "pallas" if want_pallas else "xla"
    try:
        fn1, fn3 = compile_fns(want_pallas)
    except Exception as exc:  # noqa: BLE001 — robustness for driver runs
        if not want_pallas:
            raise
        import sys
        print(f"tpcc: pallas path failed ({type(exc).__name__}: {exc}); "
              f"falling back to XLA", file=sys.stderr)
        kernel_path = "xla-fallback"
        fn1, fn3 = compile_fns(False)

    # HONEST timing (see bench_default's note): one-rep and three-rep calls
    # are each ONE dispatch + ONE pull per bucket; their difference / 2 is
    # pure device compute for one pass over all windows.
    def timed_pass(fn):
        t0 = time.perf_counter()
        outs = [fn(*args) for args in dev_stacks]
        host = [np.asarray(o) for o in outs]
        return time.perf_counter() - t0, host

    t1, h1 = timed_pass(fn1)
    t3, h3 = timed_pass(fn3)
    assert all((h == h[0]).all() for h in h3)          # reps agree
    assert all((a[0] == b[0]).all() for a, b in zip(h1, h3))
    dt = max((t3 - t1) / 2, 1e-9)

    if kernel_path == "pallas":
        # runtime cross-check of the Mosaic-compiled kernel against the XLA
        # formulation on the smallest bucket (interpret-mode equivalence is
        # tested in tests/test_pallas.py; this catches TPU-lowering-specific
        # miscompiles the interpreter cannot)
        si = min(range(len(dev_stacks)),
                 key=lambda i: dev_stacks[i][0].shape[0])
        ref = np.asarray(_tpcc_stack_fn(False, 1)(*dev_stacks[si]))
        assert (np.asarray(h1[si]) == ref[0]).all(), \
            f"pallas/XLA divergence on bucket {si}: {h1[si]} vs {ref[0]}"

    cross = sum(int(h[0][0]) for h in h1)
    inwin = sum(int(h[0][1]) for h in h1)
    max_wave = max(int(h[0][2]) for h in h1)
    emit(dict({
        "metric": "tpcc_neworder_resolve_ms",
        "value": round(dt * 1e3, 2),
        "unit": "ms",
        "platform": PLATFORM,
        "target_ms": 50.0,
        "hardware": "1 chip (target stated for v5e-8)",
        "txns": n_txns,
        "edges": cross + inwin,
        "edges_cross_window": cross,
        "edges_in_window": inwin,
        "max_wave_depth": max_wave,
        "windows": len(host_windows),
        "kernel_path": kernel_path,
        "txns_per_sec": round(n_txns / dt, 1),
        "wall_ms_with_tunnel_rtt": round(t1 * 1e3, 2),
        "host_prep_seconds": round(prep_s, 2),
    }))


# ---------------------------------------------------------------- audit ----

def bench_audit(ops=300, seed=11):
    """Audit/census overhead lane (ISSUE 7 acceptance): the measured cost
    of the always-on replica-state auditor, recorded as a percentage of
    the scalar active-scan hot loop.

    A small real burn populates a 3-replica cluster, then one full
    digest walk (every resident command, unbounded window — the worst
    case; production rounds cover only the certified [lo, hi) slice) plus
    one census sweep is timed per node.  `value` = per-resident-command
    sweep cost / per-transaction scalar deps cost x 100.  Steady-state
    model: each audit round folds every resident command once; any
    workload that admits at least one transaction per resident command
    per audit interval therefore pays at most `value` percent — the <2%
    budget tests/test_obs_budget.py enforces."""
    from accord_tpu.local.audit import census_node, digest_node
    from accord_tpu.primitives.keys import Ranges
    from accord_tpu.primitives.timestamp import Timestamp, TXNID_NONE
    from accord_tpu.sim.burn import BurnRun

    run = BurnRun(seed, ops, durability_cycle_s=2.0,
                  topology_changes=False)
    run.run()
    cluster = run.cluster
    hi = Timestamp(1 << 30, 0, 0, 0)
    total_cmds = sum(len(s.commands) for n in cluster.nodes.values()
                     for s in n.command_stores.all())
    best = None
    folded = 0
    for _ in range(3):
        t0 = time.perf_counter()
        folded = 0
        for node in cluster.nodes.values():
            topo = node.topology.current()
            for shard in topo.shards:
                if node.id in shard.nodes:
                    _d, n = digest_node(node, Ranges([shard.range]),
                                        TXNID_NONE, hi)
                    folded += n
            census_node(node)
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    per_cmd_us = best / max(1, total_cmds) * 1e6

    # the scalar hot-loop yardstick: one active-conflict scan per replica
    # (rf=3) over a 1024-entry per-key history — the same txn cost model
    # the obs budget tests price against (tests/test_obs_budget.py)
    from accord_tpu.local.cfk import CommandsForKey, InternalStatus
    from accord_tpu.primitives.keys import Key
    from accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind
    from accord_tpu.utils.random_source import RandomSource
    rng = RandomSource(3)
    cfk = CommandsForKey(Key(1))
    statuses = [InternalStatus.PREACCEPTED, InternalStatus.ACCEPTED,
                InternalStatus.COMMITTED, InternalStatus.STABLE,
                InternalStatus.APPLIED]
    hlc = 1000
    for _ in range(1024):
        hlc += 1 + rng.next_int(2)
        cfk.update(TxnId.create(1, hlc, rng.pick([TxnKind.READ,
                                                  TxnKind.WRITE]),
                                Domain.KEY, rng.next_int(8)),
                   rng.pick(statuses), None)
    probe = TxnId.create(1, hlc + 10, TxnKind.WRITE, Domain.KEY, 2)
    kinds = probe.kind.witnesses()
    sink = []
    loop_best = None
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(200):
            for _replica in range(3):
                sink.clear()
                cfk.map_reduce_active(probe, kinds, sink.append)
        dt = (time.perf_counter() - t0) / 200 * 1e6
        loop_best = dt if loop_best is None else min(loop_best, dt)

    pct = per_cmd_us / loop_best * 100.0
    emit({
        "metric": "audit_census_overhead_pct_of_scalar",
        "value": round(pct, 3),
        "unit": "pct",
        "budget_pct": 2.0,
        "sweep_us_per_resident_cmd": round(per_cmd_us, 3),
        "scalar_txn_us": round(loop_best, 1),
        "resident_cmds": total_cmds,
        "digest_folded": folded,
        "audit_rounds_at_quiesce": len(run.audit_rounds),
    })


# ------------------------------------------------------------------ slo ----

# open-loop SLO lanes (workload/openloop.py): named profiles driven through
# the pipeline host at a seeded arrival schedule, latency measured from
# INTENDED start (coordinated omission charged, not hidden).  Offered rates
# sit below the sim cluster's saturation point so the lanes measure tail
# latency under load, not pure overload queueing.  All sim lanes are fully
# deterministic (virtual time), so their guard gates only fire on real
# behavioral regressions.
SLO_SIM_LANES = {
    "slo-zipf": dict(profile="zipfian", ops=600, rate_per_s=100.0, keys=48),
    "slo-range": dict(profile="range_mix", ops=500, rate_per_s=80.0,
                      keys=48),
    "slo-tpcc": dict(profile="tpcc_neworder", ops=400, rate_per_s=60.0,
                     keys=64),
    "slo-ephemeral": dict(profile="ephemeral_read_heavy", ops=600,
                          rate_per_s=150.0, keys=48),
}


def _slo_env_overrides(lane: dict) -> dict:
    """ACCORD_SLO_OPS / ACCORD_SLO_RATE shrink a lane (guard tests);
    ACCORD_SLO_STALL_US injects a synthetic coordinator stall at 40% of
    the schedule span — a TAIL-ONLY regression (p99 up, throughput ~flat:
    arrivals keep their schedule, so the run's duration barely moves)
    that exercises the guard's tail gate end-to-end."""
    lane = dict(lane)
    if os.environ.get("ACCORD_SLO_OPS"):
        lane["ops"] = int(os.environ["ACCORD_SLO_OPS"])
    if os.environ.get("ACCORD_SLO_RATE"):
        lane["rate_per_s"] = float(os.environ["ACCORD_SLO_RATE"])
    stall_us = int(os.environ.get("ACCORD_SLO_STALL_US", "0") or 0)
    if stall_us > 0:
        span_us = lane["ops"] / lane["rate_per_s"] * 1e6
        lane["stall_at_us"] = int(0.4 * span_us)
        lane["stall_us"] = stall_us
    return lane


def bench_slo_sim(config: str, seed: int = 11):
    """One sim SLO lane: open-loop generator -> pipeline host -> per-phase
    SLO report recorded in the row and BENCH_HISTORY (`--guard` gates the
    p99/p99.9 tails against the last clean baseline)."""
    from accord_tpu.workload import run_open_loop_sim

    lane = _slo_env_overrides(SLO_SIM_LANES[config])
    profile = lane.pop("profile")
    run = run_open_loop_sim(profile=profile, seed=seed,
                            schedule=os.environ.get("ACCORD_SLO_SCHEDULE",
                                                    "poisson"),
                            **lane)
    rep = run.report
    counts = rep["counts"]
    assert counts["acked"] > 0.5 * lane["ops"], counts
    assert counts["pending"] == 0, counts
    emit({
        "metric": config.replace("-", "_") + "_txn_per_sec",
        "value": rep["achieved_per_s"],
        "unit": "txn/s",
        "workload": f"open-loop {profile} via sim pipeline host "
                    f"({rep['schedule']['kind']} arrivals)",
        "ops": lane["ops"],
        "acked": counts["acked"],
        "shed": counts["shed"],
        "offered_per_s": rep["offered_per_s"],
        "open_p99_ms": round(rep["open_loop"]["p99_us"] / 1e3, 1),
        "slo": rep,
    })


def bench_slo_tcp(config: str, profile: str, ops: int = 400,
                  rate_per_s: float = 80.0, keys: int = 64, seed: int = 7):
    """Open-loop SLO lane over the REAL multi-process TCP cluster with the
    ingest pipeline on (ACCORD_PIPELINE=1 in every node process): wall-
    clock arrivals, per-phase data joined from the submit replies.  The
    `ephemeral` config is this lane on the ephemeral-read path — the path's
    first bench coverage (ISSUE 6 satellite)."""
    from accord_tpu.workload import run_open_loop_tcp

    os.environ["ACCORD_PIPELINE"] = "1"
    os.environ.setdefault("ACCORD_PIPELINE_MAX_BATCH", "8")
    os.environ.setdefault("ACCORD_PIPELINE_MAX_WAIT_US", "2000")
    if os.environ.get("ACCORD_SLO_OPS"):
        ops = int(os.environ["ACCORD_SLO_OPS"])
    if os.environ.get("ACCORD_SLO_RATE"):
        rate_per_s = float(os.environ["ACCORD_SLO_RATE"])
    run = run_open_loop_tcp(profile=profile, ops=ops,
                            rate_per_s=rate_per_s, keys=keys, seed=seed)
    rep = run.report
    counts = rep["counts"]
    assert counts["acked"] > 0.5 * ops, counts
    if profile == "ephemeral_read_heavy":
        # the lane must actually exercise the ephemeral path: its two
        # rounds appear in the per-phase attribution
        assert "eph_deps" in rep["phases"], sorted(rep["phases"])
    emit({
        "metric": config.replace("-", "_") + "_txn_per_sec",
        "value": rep["achieved_per_s"],
        "unit": "txn/s",
        "workload": f"open-loop {profile} via TCP pipeline host "
                    f"({rep['schedule']['kind']} arrivals)",
        "nodes": 3,
        "ops": ops,
        "acked": counts["acked"],
        "shed": counts["shed"],
        "offered_per_s": rep["offered_per_s"],
        "open_p99_ms": round(rep["open_loop"]["p99_us"] / 1e3, 1),
        "slo": rep,
    })


def bench_slo_reshard(seed: int = 13):
    """Reshard-survival SLO lane (live elasticity): the open-loop zipfian
    TCP lane with a FULL membership change mid-window — a journal-backed
    node joins and bootstraps under load, the client refreshes routing
    from a topology frame, and a founding node drains and retires.  The
    row records the availability dip, before/during/after open-loop p99,
    time-to-SLO-recovery, and the zero-lost-acks + audit-agreement
    verdicts; `--guard` gates the tails like every other SLO lane and
    `--guard --dry-run` enforces the reshard row schema."""
    from accord_tpu.workload.openloop import run_reshard_tcp

    os.environ["ACCORD_PIPELINE"] = "1"
    os.environ.setdefault("ACCORD_PIPELINE_MAX_BATCH", "8")
    os.environ.setdefault("ACCORD_PIPELINE_MAX_WAIT_US", "2000")
    ops = int(os.environ.get("ACCORD_SLO_OPS", "2400"))
    rate = float(os.environ.get("ACCORD_SLO_RATE", "80"))
    frac = float(os.environ.get("ACCORD_RESHARD_AT", "0.33"))
    run = run_reshard_tcp(ops=ops, rate_per_s=rate, reshard_at_frac=frac,
                          seed=seed)
    rep = run.report
    counts = rep["counts"]
    assert counts["acked"] > 0.5 * ops, counts
    rs = rep["reshard"]
    assert rs["lost_acks"] == 0, rs["lost_detail"]
    assert rs["audit"]["agree"], rs["audit"]
    emit({
        "metric": "slo_reshard_txn_per_sec",
        "value": rep["achieved_per_s"],
        "unit": "txn/s",
        "workload": "open-loop zipfian via TCP pipeline host with a "
                    "mid-window membership reshard (join+bootstrap, "
                    "epoch gossip, drain+retire)",
        "ops": ops,
        "acked": counts["acked"],
        "shed": counts["shed"],
        "offered_per_s": rep["offered_per_s"],
        "open_p99_ms": round(rep["open_loop"]["p99_us"] / 1e3, 1),
        "availability_dip_pct": rs["availability"]["dip_pct"],
        "time_to_slo_recovery_s": rs["time_to_slo_recovery_s"],
        "lost_acks": rs["lost_acks"],
        "slo": rep,
    })


def bench_slo_overload(seed: int = 23):
    """Graceful-overload SLO lane (multi-tenant QoS): an open-loop sweep
    over the live TCP cluster from 0.5x to 10x its measured closed-loop
    capacity with mixed tenants/priority classes, the QoS admission tier
    armed in every node process (ACCORD_QOS=1) and the client honoring
    every nack's `retry_after_us` hint.  The row records the
    goodput-vs-offered curve, per-class open-loop p99, shed rate,
    retry-after honor rate, and the exact accounting identity; the lane
    asserts the graceful-degradation verdicts the subsystem exists for —
    goodput at 5x offered stays >= 90% of peak, and high-priority p99 at
    5x stays within 2x its uncontended (0.5x) value while `best_effort`
    absorbs the shed."""
    from accord_tpu.workload.openloop import run_overload_tcp

    os.environ["ACCORD_PIPELINE"] = "1"
    os.environ.setdefault("ACCORD_PIPELINE_MAX_BATCH", "8")
    os.environ.setdefault("ACCORD_PIPELINE_MAX_WAIT_US", "2000")
    os.environ["ACCORD_QOS"] = "1"
    # lane tuning for the shared 1-CPU box (all env-overridable): the
    # per-node per-tenant rate buckets set the provisioned plateau the
    # goodput curve flattens onto, the fractional inflight target keeps
    # queues (and with them high-priority latency) near-uncontended, and
    # the pressure-scaled retry floor keeps the nack/retry flood from
    # taxing the plateau
    os.environ.setdefault("ACCORD_QOS_LAG_TARGET_US", "30000")
    os.environ.setdefault("ACCORD_QOS_NORMAL_PRESSURE", "2.0")
    os.environ.setdefault("ACCORD_QOS_DEPTH_TARGET", "1.5")
    os.environ.setdefault("ACCORD_QOS_RETRY_FLOOR_US", "40000")
    os.environ.setdefault("ACCORD_QOS_RATE", "8")
    os.environ.setdefault("ACCORD_QOS_BURST", "6")
    window_s = float(os.environ.get("ACCORD_OVERLOAD_WINDOW_S", "6"))
    # multiplier anchor pinned for run-to-run reproducibility (the
    # closed-loop probe on this box swings ~2x between runs and is still
    # measured + recorded in the row); set to 0 to anchor on the probe
    cap = float(os.environ.get("ACCORD_OVERLOAD_CAPACITY", "120") or 0)
    run = run_overload_tcp(seed=seed, window_s=window_s,
                           capacity_per_s=cap if cap > 0 else None)
    rep = run.report
    ov = rep["overload"]
    acc = ov["accounting"]
    assert acc["exact"], acc
    assert acc["pending"] == 0, acc
    assert acc["shed"] > 0, \
        f"overload sweep to 10x never shed — QoS tier not engaged: {acc}"
    assert ov["goodput_at_5x_frac_of_peak"] is not None \
        and ov["goodput_at_5x_frac_of_peak"] >= 0.9, ov
    assert ov["high_p99_at_5x_us"] is not None \
        and ov["high_p99_uncontended_us"] is not None \
        and ov["high_p99_at_5x_us"] <= 2 * ov["high_p99_uncontended_us"], \
        (ov["high_p99_at_5x_us"], ov["high_p99_uncontended_us"])
    sq = ov.get("server_qos") or {}
    if sq.get("submitted"):
        # server-side identity: every admission decision is accounted
        assert sq["admitted"] + sq["shed"] + sq["throttled"] \
            == sq["submitted"], sq
    emit({
        "metric": "slo_overload_txn_per_sec",
        "value": ov["peak_goodput_per_s"],
        "unit": "txn/s",
        "workload": "open-loop overload sweep 0.5x-10x capacity via TCP "
                    "pipeline host, QoS admission armed (mixed tenants, "
                    "high/normal/best_effort, retry-after honored)",
        "nodes": 3,
        "ops": acc["submitted"],
        "acked": acc["acked"],
        "shed": acc["shed"],
        "offered_per_s": rep["offered_per_s"],
        "open_p99_ms": round(rep["open_loop"]["p99_us"] / 1e3, 1),
        "capacity_per_s": ov["capacity_per_s"],
        "goodput_at_5x_frac_of_peak": ov["goodput_at_5x_frac_of_peak"],
        "high_p99_at_5x_us": ov["high_p99_at_5x_us"],
        "retry_honor_rate": ov["retry_honor_rate"],
        "slo": rep,
    })


def bench_slo_zipf1m(seed: int = 17):
    """Bounded-memory SLO lane (replaces the retired encoder-level zipf1m
    microbench): the zipfian open-loop lane over a MILLION-key space driven
    through the REAL sim protocol path with the command store's resident
    tier capped far below the working set (local/paging.py).  After the
    load window the lane settles through durability/cleanup cycles so the
    paging ladder runs end to end — spill, refault, compaction, cleanup
    truncating the resident tier, CFK shells paging out — then asserts the
    bounded-memory verdicts: zero lost acks, resident high-water a small
    fraction of the working set, cross-replica audit agreement with the
    leak detector quiet.  The row records the paging section `--guard
    --dry-run` schema-checks alongside the exact-sample SLO quantiles."""
    from accord_tpu.local.paging import node_paging_stats
    from accord_tpu.workload import run_open_loop_sim

    ops = int(os.environ.get("ACCORD_SLO_OPS", "4000"))
    rate = float(os.environ.get("ACCORD_SLO_RATE", "300"))
    keys = int(os.environ.get("ACCORD_ZIPF1M_KEYS", "1000000"))
    settle_s = float(os.environ.get("ACCORD_ZIPF1M_SETTLE_S", "25"))
    cap = int(os.environ.get("ACCORD_RESIDENT_CMDS", "0") or "0")
    if cap <= 0:
        # <10% of the working set by a wide margin at the default shape
        cap = max(25, ops // 80)
    prev_cap = os.environ.get("ACCORD_RESIDENT_CMDS")
    os.environ["ACCORD_RESIDENT_CMDS"] = str(cap)
    try:
        run = run_open_loop_sim(profile="zipfian", ops=ops, rate_per_s=rate,
                                keys=keys, token_span=keys, seed=seed,
                                keep_cluster=True)
    finally:
        if prev_cap is None:
            os.environ.pop("ACCORD_RESIDENT_CMDS", None)
        else:
            os.environ["ACCORD_RESIDENT_CMDS"] = prev_cap
    rep = run.report
    counts = rep["counts"]
    # zero lost acks: every submitted op settled, none failed or vanished
    assert counts["pending"] == 0 and counts["failed"] == 0, counts
    assert counts["acked"] > 0.5 * ops, counts

    # settle: durability rounds fence the history, cleanup truncates the
    # resident tier, CFK shells empty and page out
    cluster = run.cluster
    end_s = cluster.now_s + settle_s
    cluster.process_until(lambda: cluster.now_s >= end_s,
                          max_items=50_000_000)

    # refault probe — the bounded-memory analogue of the reshard lane's
    # zero-lost-acks re-read: a sample of spilled commands per store must
    # fault back intact through the public access path.  At steady state
    # nothing else touches a quiescent command again (that is the point of
    # the eligibility rule, and why organic refaults go to zero as the key
    # space grows), so the lane drives the fault machinery itself.
    hw = 0
    probed = 0
    for node in cluster.nodes.values():
        for store in node.command_stores.all():
            pager = getattr(store, "pager", None)
            if pager is None:
                continue
            hw = max(hw, pager.resident_high_water)  # pre-probe high-water
            for txn_id in list(pager.spilled)[:32]:
                cmd = store.commands[txn_id]
                assert cmd is not None and cmd.save_status.name in (
                    "APPLIED", "INVALIDATED", "TRUNCATED_APPLY",
                    "ERASED"), (txn_id, cmd)
                assert txn_id not in pager.spilled, txn_id
                probed += 1
    assert probed > 0, "nothing left spilled to probe"

    # the burn's end-of-run checker: census (leak detector) + audit rounds
    cluster.attach_auditors(interval_s=0.0)
    leak_alarms = 0
    for a in cluster.auditors.values():
        census = a.census_once()
        leak_alarms += 1 if census["leak_alarm"] else 0
    done = {}
    for nid, a in cluster.auditors.items():
        a.audit_once(on_done=lambda r, n=nid: done.__setitem__(n, r))
    cluster.process_until(lambda: len(done) == len(cluster.auditors),
                          max_items=5_000_000)
    outcomes = [rd["outcome"] for r in done.values() if r
                for rd in r["rounds"]]
    divergences = [d for a in cluster.auditors.values()
                   for d in a.divergences]
    assert outcomes and not divergences, (outcomes, divergences)
    assert leak_alarms == 0, "paged-out state tripped the leak detector"

    per_node = [node_paging_stats(n) for n in cluster.nodes.values()]
    assert all(p is not None for p in per_node), "paging tier never armed"
    working_set = counts["acked"]
    hits = sum(p["hits"] for p in per_node)
    misses = sum(p["misses"] for p in per_node)
    paging = {
        "cap": cap,
        "working_set": working_set,
        "resident_high_water": hw,
        "resident": max(p["resident"] for p in per_node),
        "spilled": max(p["spilled"] for p in per_node),
        "hit_rate": round(hits / max(1, hits + misses), 4),
        "evictions": sum(p["evictions"] for p in per_node),
        "refaults": sum(p["refaults"] for p in per_node),
        "refault_probe": probed,
        "cfk_evictions": sum(p["cfk_evictions"] for p in per_node),
        "cfk_restores": sum(p["cfk_restores"] for p in per_node),
        "spill_disk_bytes": max(p["spill_disk_bytes"] for p in per_node),
        "spill_compactions": sum(p["spill_compactions"] for p in per_node),
        "lost_acks": 0,
        "leak_alarms": leak_alarms,
        "audit_agree": not divergences,
    }
    for p in per_node:
        assert p["evictions"] > 0, "budget never forced an eviction"
    # high-water may transiently exceed the cap (in-flight commands are
    # not evictable; evictions run at op boundaries) but must stay a
    # small multiple of it and — the paper-level claim — a small fraction
    # of the working set.  Ratio gates only on full-size runs: a guard-
    # shrunk window (ACCORD_SLO_OPS) has no meaningful working set.
    assert hw <= 2 * cap + 64, paging
    if ops >= 1000:
        assert cap < 0.10 * working_set, paging
        assert hw < 0.10 * working_set, paging
        assert paging["refaults"] > 0, paging
        if settle_s >= 10:
            assert paging["cfk_evictions"] > 0, paging
    rep["paging"] = paging
    emit({
        "metric": "slo_zipf1m_txn_per_sec",
        "value": rep["achieved_per_s"],
        "unit": "txn/s",
        "workload": f"open-loop zipfian over {keys} keys via sim pipeline "
                    f"host, resident tier capped at {cap} commands/store "
                    f"(journal-backed paging)",
        "ops": ops,
        "acked": counts["acked"],
        "shed": counts["shed"],
        "offered_per_s": rep["offered_per_s"],
        "open_p99_ms": round(rep["open_loop"]["p99_us"] / 1e3, 1),
        "resident_high_water": hw,
        "hit_rate": paging["hit_rate"],
        "slo": rep,
    })


def bench_slo_wan(seed: int = 29):
    """Multi-DC WAN SLO lane (geo-placement harness): the open-loop sim
    lane on a geo-placed cluster — topology/geo.wan3_profile's hub DC
    holding the full slow quorum plus three single-node DCs at 50/100/160
    ms injected RTT — swept over (electorate, coordinator placement)
    configurations.  The headline is the paper's signature property:
    client-visible commit in ONE WAN round trip when the coordinator sits
    inside a minimal fast-path electorate spanning the nearest WAN DC, so
    the row records open-loop p50/p99 as MULTIPLES of the injected WAN RTT
    (lower is better) next to the fast-path ratio and the per-link-class
    message census (WAN crossings/txn).  The all-replica electorate and
    the coordinator-outside placement must both be measurably worse —
    that spread is the yardstick the geo-placement tuning space is judged
    against.  A fourth arm severs the electorate's WAN DC mid-run
    (DcPartitionNemesis) and records the fast-path ratio degrading to the
    slow path and recovering after heal, with the end-of-run census +
    audit checkers green.  The flat-latency tcp lane's messages/txn rides
    along as the recorded baseline for ROADMAP's structural
    message-reduction item."""
    from accord_tpu.topology.geo import wan3_profile
    from accord_tpu.workload.openloop import run_wan_sim

    ops = int(os.environ.get("ACCORD_SLO_OPS", "240"))
    rate = float(os.environ.get("ACCORD_SLO_RATE", "30"))
    keys = int(os.environ.get("ACCORD_WAN_KEYS", "240"))
    geo = wan3_profile()
    # the yardstick every latency in the row is expressed against: one
    # round trip between the hub and the electorate's nearest WAN DC
    rtt = geo.rtt_us("dc_a", "dc_b")
    minimal = frozenset({1, 2, 3, 5})  # fq=3: hub pair + dc_b, any 3 of 4
    full = ops >= 150  # verdicts gate only on full-size runs (guard smoke
    #                    may shrink via ACCORD_SLO_OPS)

    sweep = []
    head = rep = None
    for name, electorate, origin in (
            ("span-min-in", minimal, 1),   # headline: 1 WAN RTT
            ("all-in", None, 1),           # fq=6 gates on 2nd WAN DC
            ("min-out", minimal, 5)):      # coordinator outside the hub
        run = run_wan_sim(electorate=electorate, origin=origin, ops=ops,
                          rate_per_s=rate, seed=seed, keys=keys, geo=geo)
        r = run.report
        counts = r["counts"]
        assert counts["pending"] == 0 and counts["failed"] == 0, \
            (name, counts)
        wan = run.summary["wan"]
        arm = {
            "config": name,
            "origin": run.schedule["origin"],
            "origin_dc": geo.dc_of(run.schedule["origin"]),
            "electorate": sorted(electorate) if electorate else None,
            "fast_path_ratio": r["fast_path_ratio"],
            "p50_rtt_multiple": round(r["open_loop"]["p50_us"] / rtt, 3),
            "p99_rtt_multiple": round(r["open_loop"]["p99_us"] / rtt, 3),
            "open_p50_us": r["open_loop"]["p50_us"],
            "open_p99_us": r["open_loop"]["p99_us"],
            "wan_crossings_per_txn": wan["wan_crossings_per_txn"],
            "msgs_per_txn": wan["msgs_per_txn"],
            "dcs": wan["dcs"],
            "by_elect": wan["by_elect"],
        }
        sweep.append(arm)
        if name == "span-min-in":
            head, rep = arm, r

    # the lane's reason to exist: the minimal-electorate fast path commits
    # in ~one WAN round trip, and both degenerate configurations pay for it
    assert head["fast_path_ratio"] is not None \
        and head["fast_path_ratio"] >= 0.8, head
    if full:
        assert head["p50_rtt_multiple"] <= 1.2, head
        for worse in sweep[1:]:
            assert worse["p50_rtt_multiple"] \
                >= head["p50_rtt_multiple"] + 0.4, (head, worse)

    # partition arm: sever dc_b (the electorate's WAN member) for the
    # middle of the run — fast quorum unreachable, the hub-local slow
    # quorum keeps committing; ratio degrades then recovers after heal
    dur_us = int(ops / rate * 1e6)
    begin_us, end_us = int(0.25 * dur_us), int(0.66 * dur_us)
    prun = run_wan_sim(electorate=minimal, origin=1, ops=ops,
                       rate_per_s=rate, seed=seed + 1, keys=keys, geo=geo,
                       partition=("dc_b", begin_us, end_us),
                       keep_cluster=True)
    pcounts = prun.report["counts"]
    assert pcounts["pending"] == 0 and pcounts["failed"] == 0, pcounts
    windows = prun.report["partition"]["windows"]
    if full:
        assert windows["before"]["fast_path_ratio"] >= 0.8, windows
        assert windows["during"]["fast_path_ratio"] is not None \
            and windows["during"]["fast_path_ratio"] < 0.5, windows
        assert windows["after"]["fast_path_ratio"] >= 0.8, windows

    # the burn's end-of-run checkers on the partition arm's cluster:
    # census (leak detector) + cross-replica audit must be green — a
    # severed-and-healed DC with divergent replicas must fail the lane
    cluster = prun.cluster
    cluster.attach_auditors(interval_s=0.0)
    leak_alarms = sum(1 for a in cluster.auditors.values()
                      if a.census_once()["leak_alarm"])
    done = {}
    for nid, a in cluster.auditors.items():
        a.audit_once(on_done=lambda r_, n=nid: done.__setitem__(n, r_))
    cluster.process_until(lambda: len(done) == len(cluster.auditors),
                          max_items=5_000_000)
    outcomes = [rd["outcome"] for r_ in done.values() if r_
                for rd in r_["rounds"]]
    divergences = [d for a in cluster.auditors.values()
                   for d in a.divergences]
    assert outcomes and not divergences, (outcomes, divergences)
    assert leak_alarms == 0, "partition arm tripped the leak detector"

    # flat-latency tcp lane's messages/txn: the recorded baseline row for
    # ROADMAP's structural message-reduction yardstick (the wan arms'
    # msgs_per_txn census is compared against this number)
    flat = None
    trow = _load_history().get("tcp", {}).get("host") or {}
    tobs = trow.get("obs") or {}
    tok = (tobs.get("outcomes") or {}).get("ok", 0)
    tmsgs = (tobs.get("transport") or {}).get("msgs", 0)
    if tok and tmsgs:
        flat = {"msgs_per_txn": round(tmsgs / tok, 2),
                "source": "BENCH_HISTORY tcp/host",
                "unix": trow.get("unix")}

    rep["wan"] = {
        "rtt_us": rtt,
        "wan_link": ["dc_a", "dc_b"],
        "profile": geo.name,
        "headline_config": "span-min-in",
        "sweep": sweep,
        "partition": {
            "dc": "dc_b",
            "begin_us": begin_us,
            "end_us": end_us,
            "windows": windows,
            "lost_acks": pcounts["failed"] + pcounts["pending"],
            "audit": {"agree": not divergences, "rounds": len(outcomes),
                      "leak_alarms": leak_alarms},
        },
        "flat_tcp_baseline": flat,
    }
    emit({
        "metric": "slo_wan_p50_rtt_multiple",
        "value": head["p50_rtt_multiple"],
        "unit": "x WAN RTT",
        "workload": f"open-loop uniform over {keys} keys via geo-placed "
                    f"sim ({geo.name}: hub slow quorum + 3 WAN DCs, "
                    f"injected WAN RTT {rtt / 1000:.0f}ms), electorate "
                    "sweep + dc_b partition arm",
        "nodes": len(geo.node_dc),
        "ops": ops,
        "acked": rep["counts"]["acked"],
        "fast_path_ratio": head["fast_path_ratio"],
        "p99_rtt_multiple": head["p99_rtt_multiple"],
        "wan_crossings_per_txn": head["wan_crossings_per_txn"],
        "all_in_p50_rtt_multiple": sweep[1]["p50_rtt_multiple"],
        "min_out_p50_rtt_multiple": sweep[2]["p50_rtt_multiple"],
        "partition_during_ratio": windows["during"]["fast_path_ratio"],
        "partition_after_ratio": windows["after"]["fast_path_ratio"],
        "slo": rep,
    })


# ---------------------------------------------------------------- guard ----

GUARD_PCT = 15.0  # per-kernel (and headline) regression threshold, percent

# tail gates need enough samples to be meaningful and an absolute floor so
# microsecond-level noise on a wall-clock lane cannot trip a percentage
SLO_GUARD_MIN_COUNT = 20
SLO_GUARD_FLOOR_US = 500

# per-verb protocol-CPU gates (the "cpu" row key, obs/cpuprof.py): same
# sample-count discipline; the floor is lower because per-dispatch applies
# sit in the tens-to-hundreds of us (the env override lets the guard tests
# exercise the gate on small runs whose baselines sit under the floor)
CPU_GUARD_MIN_COUNT = 20
CPU_GUARD_FLOOR_US = float(os.environ.get("ACCORD_CPU_GUARD_FLOOR_US", "20"))


def _load_history() -> dict:
    try:
        with open(HISTORY_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _guard_problems(current: dict, baseline: dict) -> list:
    """Regressions of `current` vs the last clean `baseline` row: the
    headline metric (direction-aware) and every per-kernel profile p50."""
    problems = []
    bval, cval = baseline.get("value"), current.get("value")
    if isinstance(bval, (int, float)) and isinstance(cval, (int, float)) \
            and bval:
        pct = (cval - bval) / bval * 100.0
        if CONFIG in LOWER_IS_BETTER:
            pct = -pct
        if pct < -GUARD_PCT:
            problems.append(
                f"headline {current.get('metric', CONFIG)}: {bval} -> "
                f"{cval} ({pct:+.1f}%)")
    bkern = (baseline.get("profile") or {}).get("kernels", {})
    ckern = (current.get("profile") or {}).get("kernels", {})
    for kernel, c in sorted(ckern.items()):
        b = bkern.get(kernel)
        if not b or not b.get("p50"):
            continue
        if c.get("p50", 0) > b["p50"] * (1 + GUARD_PCT / 100.0):
            problems.append(
                f"kernel {kernel}: p50 {b['p50']}us -> {c['p50']}us "
                f"(+{(c['p50'] / b['p50'] - 1) * 100:.0f}%)")
    problems.extend(_slo_problems(current, baseline))
    problems.extend(_cpu_problems(current, baseline))
    return problems


def _cpu_problems(current: dict, baseline: dict) -> list:
    """Per-verb protocol-CPU regressions vs the baseline row's "cpu" key:
    each verb's exact-sample per-dispatch p50 (obs/cpuprof.py) gates at
    GUARD_PCT exactly like the per-kernel profile p50s — the yardstick the
    coming `local/` optimizations are judged against must also be the
    tripwire that catches their regressions."""
    problems: list = []
    cver = (current.get("cpu") or {}).get("verbs") or {}
    bver = (baseline.get("cpu") or {}).get("verbs") or {}
    for verb, c in sorted(cver.items()):
        b = bver.get(verb)
        if not b:
            continue
        if min(b.get("count", 0), c.get("count", 0)) < CPU_GUARD_MIN_COUNT:
            continue
        bv, cv = b.get("p50_us"), c.get("p50_us")
        if not bv or not cv or bv < CPU_GUARD_FLOOR_US:
            continue
        if cv > bv * (1 + GUARD_PCT / 100.0):
            problems.append(
                f"cpu verb {verb}: p50 {bv}us -> {cv}us "
                f"(+{(cv / bv - 1) * 100:.0f}%)")
    return problems


def _validate_cpu_schema(cpu: dict, where: str) -> None:
    """The "cpu" row contract `--guard --dry-run` enforces on BENCH_HISTORY
    (the same schema-rot discipline as the SLO rows): exact-sample
    provenance, per-verb quantiles with stage splits, and the top-verbs
    table the per-verb gate and the `local/` optimization work read."""
    assert cpu.get("quantile_source") == "exact-sample", \
        f"{where}: cpu rows must use exact-sample quantiles"
    verbs = cpu.get("verbs")
    assert isinstance(verbs, dict) and verbs, f"{where}: missing cpu verbs"
    for verb, q in verbs.items():
        for k in ("count", "p50_us", "p99_us", "dispatches",
                  "est_total_ms", "stages"):
            assert k in q, f"{where}: cpu verb {verb} missing {k}"
        assert isinstance(q["stages"], dict), f"{where}: {verb} stages"
        for st, sq in q["stages"].items():
            assert "p50_us" in sq and "count" in sq, \
                f"{where}: cpu verb {verb} stage {st}"
    assert isinstance(cpu.get("top"), list) and cpu["top"], \
        f"{where}: missing cpu top table"
    assert cpu.get("sampled", 0) > 0 and cpu.get("dispatches", 0) > 0, \
        f"{where}: cpu row with no samples"


def _slo_tail_check(what: str, b: dict, c: dict, quantiles,
                    problems: list) -> None:
    if min(b.get("count", 0), c.get("count", 0)) < SLO_GUARD_MIN_COUNT:
        return
    for q in quantiles:
        bv, cv = b.get(q), c.get(q)
        if not bv or not cv or bv < SLO_GUARD_FLOOR_US:
            continue
        if cv > bv * (1 + GUARD_PCT / 100.0):
            problems.append(
                f"slo {what} {q}: {bv}us -> {cv}us "
                f"(+{(cv / bv - 1) * 100:.0f}%)")


def _slo_problems(current: dict, baseline: dict) -> list:
    """Tail-latency regressions of an SLO row vs its baseline: the open-
    loop p99/p99.9 (the lane's reason to exist — intended-start latency
    that charges coordinated omission) and every per-phase p99.  A tail-
    only slowdown (p99 up, throughput flat) therefore fails the guard even
    though the headline metric moved nothing."""
    problems: list = []
    cslo = current.get("slo") or {}
    bslo = baseline.get("slo") or {}
    if not cslo or not bslo:
        return problems
    _slo_tail_check("open_loop", bslo.get("open_loop") or {},
                    cslo.get("open_loop") or {},
                    ("p99_us", "p999_us"), problems)
    bphases = bslo.get("phases") or {}
    for ph, c in sorted((cslo.get("phases") or {}).items()):
        b = bphases.get(ph)
        if b:
            _slo_tail_check(f"phase {ph}", b, c, ("p99_us",), problems)
    return problems


def _validate_slo_schema(slo: dict, where: str) -> None:
    """The SLO row contract `--guard --dry-run` enforces on BENCH_HISTORY
    (schema rot in a recorded lane must fail CI, not silently stop
    gating).  Every quantile section must be the exact-sample path —
    bucket quantiles false-trip a 15% gate (PR-3 precedent)."""
    assert slo.get("quantile_source") == "exact-sample", \
        f"{where}: slo rows must use exact-sample quantiles"
    for sec in ("open_loop", "closed_loop"):
        q = slo.get(sec)
        assert isinstance(q, dict) and "count" in q, f"{where}: missing {sec}"
        if q["count"]:
            for k in ("p50_us", "p99_us", "p999_us", "mean_us", "max_us"):
                assert k in q, f"{where}: {sec} missing {k}"
    phases = slo.get("phases")
    assert isinstance(phases, dict) and phases, f"{where}: missing phases"
    for ph, q in phases.items():
        assert "p99_us" in q and "count" in q, f"{where}: phase {ph}"
    for k in ("offered_per_s", "achieved_per_s", "counts", "shed_rate",
              "schedule"):
        assert k in slo, f"{where}: missing {k}"
    if where.startswith("slo-reshard") or "reshard" in slo:
        # reshard-survival row contract: the elasticity verdicts the lane
        # exists to record must be present and clean — a recorded baseline
        # with lost acks or no measured recovery must fail CI, not gate
        rs = slo.get("reshard")
        assert isinstance(rs, dict), f"{where}: missing reshard section"
        assert rs.get("lost_acks") == 0, \
            f"{where}: reshard row with lost acks: {rs.get('lost_acks')}"
        assert isinstance(rs.get("time_to_slo_recovery_s"), (int, float)), \
            f"{where}: reshard row without a measured SLO recovery time"
        for k in ("windows", "availability", "events", "audit"):
            assert k in rs, f"{where}: reshard missing {k}"
        for w in ("before", "during", "after"):
            assert w in rs["windows"], f"{where}: reshard window {w}"
        assert rs["audit"].get("agree") is True, \
            f"{where}: reshard row with audit divergence"
    if where.startswith("slo-overload") or "overload" in slo:
        # graceful-overload row contract: the lane exists to record that
        # the node degraded GRACEFULLY past saturation — a recorded
        # baseline with broken accounting or collapsed goodput must fail
        # CI, not gate
        ov = slo.get("overload")
        assert isinstance(ov, dict), f"{where}: missing overload section"
        for k in ("capacity_probe", "capacity_per_s", "windows",
                  "peak_goodput_per_s", "accounting", "retry_honor_rate"):
            assert k in ov, f"{where}: overload missing {k}"
        acc = ov["accounting"]
        assert acc.get("exact") is True, \
            f"{where}: overload accounting identity broken: {acc}"
        assert (acc.get("acked", 0) + acc.get("shed", 0)
                + acc.get("failed", 0) + acc.get("pending", 0)
                == acc.get("submitted")), \
            f"{where}: overload accounting does not balance: {acc}"
        assert acc.get("pending") == 0, \
            f"{where}: overload row with pending ops: {acc}"
        ws = ov["windows"]
        assert isinstance(ws, list) and ws, f"{where}: empty sweep"
        for w in ws:
            for k in ("multiplier", "offered_per_s", "goodput_per_s",
                      "shed_rate", "classes"):
                assert k in w, f"{where}: overload window missing {k}"
        g5 = ov.get("goodput_at_5x_frac_of_peak")
        assert isinstance(g5, (int, float)) and g5 >= 0.9, \
            f"{where}: goodput collapsed past saturation: {g5}"
        hp5, hp0 = ov.get("high_p99_at_5x_us"), \
            ov.get("high_p99_uncontended_us")
        assert hp5 is not None and hp0 and hp5 <= 2 * hp0, \
            f"{where}: high-priority p99 blew out under overload: " \
            f"{hp5}us vs {hp0}us uncontended"
    if where.startswith("slo-zipf1m") or "paging" in slo:
        # bounded-memory row contract: the lane exists to record that a
        # million-key working set ran through the real protocol path
        # inside a capped resident tier — a recorded baseline without the
        # paging verdicts (or with lost acks / an audit divergence) must
        # fail CI, not gate
        pg = slo.get("paging")
        assert isinstance(pg, dict), f"{where}: missing paging section"
        for k in ("cap", "working_set", "resident_high_water", "hit_rate",
                  "evictions", "refaults", "spilled", "cfk_evictions",
                  "spill_disk_bytes"):
            assert k in pg, f"{where}: paging missing {k}"
        assert pg.get("lost_acks") == 0, \
            f"{where}: paging row with lost acks: {pg.get('lost_acks')}"
        assert pg.get("audit_agree") is True, \
            f"{where}: paging row with audit divergence"
    if where.startswith("slo-wan") or "wan" in slo:
        # multi-DC WAN row contract: the lane exists to record the
        # one-WAN-RTT fast path and its degradations — a recorded baseline
        # missing the fast-path ratio, not expressing latency as a
        # multiple of the injected RTT, or with a broken partition arm
        # must fail CI, not gate
        wan = slo.get("wan")
        assert isinstance(wan, dict), f"{where}: missing wan section"
        assert isinstance(wan.get("rtt_us"), (int, float)) \
            and wan["rtt_us"] > 0, f"{where}: wan row without injected RTT"
        sweep = wan.get("sweep")
        assert isinstance(sweep, list) and sweep, f"{where}: empty sweep"
        for arm in sweep:
            for k in ("config", "origin_dc", "electorate",
                      "fast_path_ratio", "p50_rtt_multiple",
                      "p99_rtt_multiple", "wan_crossings_per_txn",
                      "msgs_per_txn", "dcs"):
                assert k in arm, \
                    f"{where}: wan arm {arm.get('config')} missing {k}"
            assert isinstance(arm["p99_rtt_multiple"], (int, float)), \
                f"{where}: {arm['config']} p99 not an RTT multiple"
        heads = [a for a in sweep
                 if a["config"] == wan.get("headline_config")]
        assert heads, f"{where}: headline config absent from sweep"
        assert isinstance(heads[0].get("fast_path_ratio"), (int, float)) \
            and heads[0]["fast_path_ratio"] >= 0.8, \
            f"{where}: headline fast_path_ratio broken: " \
            f"{heads[0].get('fast_path_ratio')}"
        pt = wan.get("partition")
        assert isinstance(pt, dict), f"{where}: missing partition arm"
        for w in ("before", "during", "after"):
            assert w in (pt.get("windows") or {}), \
                f"{where}: partition window {w}"
        assert pt.get("lost_acks") == 0, \
            f"{where}: partition arm lost acks: {pt.get('lost_acks')}"
        assert (pt.get("audit") or {}).get("agree") is True, \
            f"{where}: partition arm with audit divergence"


def _guard_baseline(result: dict):
    """The last clean same-platform-class row for this config, captured by
    emit() before it overwrote the entry (stale rows never gate)."""
    prev = result.get("prev_same_platform")
    if not prev or prev.get("stale"):
        return None
    return prev


def run_guard(result: dict) -> int:
    """`--guard`: diff the fresh row against the last clean baseline; on a
    >GUARD_PCT regression restore the baseline (the failed row is retired
    into `superseded` with stale+guard_failed marks) and exit nonzero."""
    import sys
    baseline = _guard_baseline(result)
    if baseline is None:
        print(f"# guard: no clean baseline for config={CONFIG}; "
              f"recorded this run as the baseline", file=sys.stderr)
        return 0
    problems = _guard_problems(result, baseline)
    if not problems:
        print(f"# guard: OK vs baseline of unix={baseline.get('unix')}",
              file=sys.stderr)
        return 0
    for p in problems:
        print(f"# GUARD REGRESSION ({CONFIG}): {p}", file=sys.stderr)
    # keep the history trustworthy: the regressed row must not become the
    # next run's baseline
    try:
        pclass = _platform_class(result["platform"]) \
            if result.get("platform") else "host"
        history = _load_history()
        lane = history.setdefault(CONFIG, {})
        failed = lane.get(pclass)
        if failed is not None:
            failed = dict(failed)
            failed["guard_failed"] = True
            _supersede(lane, failed, "guard regression")
        restored = dict(baseline)
        restored.pop("stale", None)
        restored.pop("stale_reason", None)
        lane[pclass] = restored
        tmp = f"{HISTORY_PATH}.tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(history, f, indent=1)
        os.replace(tmp, HISTORY_PATH)
    except OSError:
        pass
    return 2


def run_guard_dry(config: str) -> int:
    """`--guard --dry-run`: no workload — parse the history, find this
    config's rows, and diff each against itself (zero regressions by
    construction).  Exercises the whole guard parsing path so schema rot
    in BENCH_HISTORY.json fails fast in CI."""
    history = _load_history()
    lane = history.get(config, {})
    checked = []
    for pclass, entry in lane.items():
        if pclass == "superseded" or not isinstance(entry, dict):
            continue
        probe = dict(entry)
        probe["metric"] = config
        probe["prev_same_platform"] = entry
        assert not _guard_problems(probe, entry), \
            f"self-diff of {config}/{pclass} reported a regression"
        row = {
            "pclass": pclass, "value": entry.get("value"),
            "stale_superseded": len(lane.get("superseded", [])),
            "profile_kernels": sorted(
                (entry.get("profile") or {}).get("kernels", {})),
        }
        if "slo" in entry:
            # SLO-row schema validation: the tail gate reads these fields
            _validate_slo_schema(entry["slo"], f"{config}/{pclass}")
            row["slo_open_p99_us"] = entry["slo"]["open_loop"].get("p99_us")
            row["slo_phases"] = sorted(entry["slo"]["phases"])
        if "cpu" in entry:
            # CPU-row schema validation: the per-verb gate reads these
            _validate_cpu_schema(entry["cpu"], f"{config}/{pclass}")
            row["cpu_verbs"] = sorted(entry["cpu"]["verbs"])
            row["cpu_top"] = [v for v, _ms, _share in entry["cpu"]["top"]]
        checked.append(row)
    print(json.dumps({"metric": f"{config}_guard", "dry_run": True,
                      "history": HISTORY_PATH, "baselines": checked}))
    return 0


# ----------------------------------------------------------------- fill ----

# device configs cheapest-first with generous per-config subprocess
# timeouts: any short live-tunnel window fills the cheap rows before the
# expensive ones get a chance to be interrupted
FILL_CONFIGS = (("default", 600), ("rangestress", 900), ("tpcc", 2400))


def fill_device_rows(max_wait_s: float, only=None) -> int:
    """Tunnel-flap-resilient capture of the on-chip device rows.

    Each config runs in a SUBPROCESS with a hard timeout, so a tunnel that
    dies mid-run (the round-3 failure mode: hangs, not errors) is killed
    and retried instead of wedging the filler.  A completed on-chip row is
    checkpointed to BENCH_DEVICE_ROWS.json the moment it lands.  Between
    attempts the backend is re-probed (subprocess, bounded) and the filler
    backs off while the tunnel is dead.  Returns the number of configs
    still missing on exit."""
    import subprocess
    import sys
    import tempfile

    from accord_tpu.utils.backend import resolve_platform

    here = os.path.dirname(os.path.abspath(__file__))
    # resolve_platform's CPU fallback mutates JAX_PLATFORMS in THIS process
    # (required for in-process jax use; poisonous for a long-lived prober):
    # snapshot the ambient platform and restore before every probe, and run
    # the config subprocesses under the pristine environment
    ambient_platform = os.environ.get("JAX_PLATFORMS")
    ambient_env = dict(os.environ)

    def probe_platform() -> str:
        if ambient_platform is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = ambient_platform
        return resolve_platform()
    pending = [(c, t) for c, t in FILL_CONFIGS
               if only is None or c in only]
    rows = _load_rows()
    pending = [(c, t) for c, t in pending
               if not rows.get(c, {}).get("platform", "").startswith("axon")]
    deadline = time.time() + max_wait_s
    backoff = 60.0
    while pending and time.time() < deadline:
        platform = probe_platform()
        if platform.startswith("cpu"):
            wait = min(backoff, max(0.0, deadline - time.time()))
            print(f"# tunnel dead ({platform}); {len(pending)} rows "
                  f"pending; backing off {wait:.0f}s", flush=True)
            if wait <= 0:
                break
            time.sleep(wait)
            backoff = min(backoff * 2, 600.0)
            continue
        backoff = 60.0
        cfg, tmo = pending[0]
        out_path = tempfile.mktemp(prefix=f"bench_{cfg}_", suffix=".json")
        print(f"# tunnel live ({platform}); running {cfg} "
              f"(timeout {tmo}s)", flush=True)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(here, "bench.py"),
                 "--config", cfg, "--json-out", out_path],
                timeout=tmo, capture_output=True, text=True, cwd=here,
                env=ambient_env)
        except subprocess.TimeoutExpired:
            print(f"# {cfg} timed out after {tmo}s (tunnel flap?); "
                  f"will retry", flush=True)
            continue
        result = None
        try:
            with open(out_path) as f:
                result = json.loads(f.read())
        except (OSError, ValueError):
            pass
        finally:
            try:
                os.unlink(out_path)
            except OSError:
                pass
        if proc.returncode != 0 or result is None:
            tail = (proc.stderr or "")[-500:]
            print(f"# {cfg} failed (rc={proc.returncode}): {tail}",
                  flush=True)
            time.sleep(30)
            continue
        result["captured_unix"] = int(time.time())
        _store_row(cfg, result)
        plat = result.get("platform", "?")
        print(f"# {cfg} captured on platform={plat}: "
              f"{result.get('value')} {result.get('unit')}", flush=True)
        if plat.startswith("cpu"):
            # ran, but on the CPU fallback (tunnel died between probe and
            # run): keep it pending for a live window
            continue
        pending.pop(0)
    return len(pending)


def main():
    global PLATFORM, JSON_OUT, CONFIG
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="default",
                    choices=["default", "rangestress", "tpcc",
                             "maelstrom", "maelstrom-rw", "tcp",
                             "pipeline", "scalar", "journal",
                             "slo-zipf", "slo-range", "slo-tpcc",
                             "slo-ephemeral", "slo-tcp", "ephemeral",
                             "slo-journal", "slo-reshard", "slo-overload",
                             "slo-zipf1m", "slo-wan", "audit",
                             "multicore"])
    ap.add_argument("--guard", action="store_true",
                    help="after the run, diff the row (headline + per-"
                         "kernel profile p50s) against the last clean "
                         "baseline in BENCH_HISTORY.json; exit 2 on a "
                         ">15%% regression (the failed row is retired as "
                         "stale, the baseline restored)")
    ap.add_argument("--dry-run", action="store_true",
                    help="--guard only: skip the workload, parse the "
                         "history and self-diff this config's rows (CI "
                         "smoke for guard-mode parsing)")
    ap.add_argument("--json-out", default=None,
                    help="also write the JSON line to this path")
    ap.add_argument("--fill", action="store_true",
                    help="resiliently capture all on-chip device rows to "
                         "BENCH_DEVICE_ROWS.json (retries across tunnel "
                         "flaps)")
    ap.add_argument("--max-wait", type=float, default=3600.0,
                    help="--fill: give up after this many seconds")
    ap.add_argument("--only", default=None,
                    help="--fill: comma-separated subset of configs")
    ns = ap.parse_args()
    JSON_OUT = ns.json_out
    CONFIG = ns.config
    if ns.config in ("tcp", "pipeline") \
            and os.environ.get("ACCORD_TCP_DEVICE_STORE", "") == "1":
        # device-store host runs get their own regression-history lane:
        # comparing them against scalar-host numbers would flag the mode
        # switch, not a code regression
        CONFIG = ns.config + "+device"
    if ns.fill:
        only = set(ns.only.split(",")) if ns.only else None
        missing = fill_device_rows(ns.max_wait, only)
        print(f"# fill done; {missing} configs still missing")
        raise SystemExit(0 if missing == 0 else 1)
    if ns.dry_run:
        raise SystemExit(run_guard_dry(CONFIG))
    if ns.config not in ("maelstrom", "maelstrom-rw", "tcp", "pipeline",
                         "scalar", "journal", "slo-zipf", "slo-range",
                         "slo-tpcc", "slo-ephemeral", "slo-tcp",
                         "ephemeral", "slo-journal", "slo-reshard",
                         "slo-overload", "slo-zipf1m", "slo-wan",
                         "audit", "multicore"):
        # device-using configs probe the (possibly dead-tunneled) backend
        # first; host-only configs never touch the chip
        from accord_tpu.utils.backend import resolve_platform
        PLATFORM = resolve_platform()
    if ns.config == "default":
        bench_default()
    elif ns.config == "tpcc":
        bench_tpcc()
    elif ns.config == "maelstrom":
        bench_maelstrom(nodes=3, keys=100, single_key=True)
    elif ns.config == "maelstrom-rw":
        bench_maelstrom(nodes=5, keys=20, single_key=False)
    elif ns.config == "tcp":
        bench_tcp(nodes=3, keys=100)
    elif ns.config == "pipeline":
        bench_pipeline(nodes=3, keys=100)
    elif ns.config == "scalar":
        bench_scalar()
    elif ns.config == "journal":
        bench_journal()
    elif ns.config in SLO_SIM_LANES:
        bench_slo_sim(ns.config)
    elif ns.config == "slo-tcp":
        bench_slo_tcp("slo-tcp", "zipfian", ops=400, rate_per_s=80.0)
    elif ns.config == "ephemeral":
        bench_slo_tcp("ephemeral", "ephemeral_read_heavy", ops=400,
                      rate_per_s=100.0)
    elif ns.config == "slo-journal":
        # the durability tier in the tail story (ISSUE 7 satellite): the
        # zipfian open-loop lane with the fsync-durable WAL in every node
        # process (group commit, durability-gated acks).  The stall arm
        # rides ACCORD_JOURNAL_STALL_US/_AFTER — injected in the WAL
        # flush thread, not at the coordinator door (journal/wal.py).
        import tempfile
        os.environ.setdefault(
            "ACCORD_JOURNAL",
            tempfile.mkdtemp(prefix="accord-slo-journal-"))
        bench_slo_tcp("slo-journal", "zipfian", ops=400, rate_per_s=80.0)
    elif ns.config == "slo-reshard":
        bench_slo_reshard()
    elif ns.config == "slo-overload":
        bench_slo_overload()
    elif ns.config == "slo-zipf1m":
        bench_slo_zipf1m()
    elif ns.config == "slo-wan":
        bench_slo_wan()
    elif ns.config == "audit":
        bench_audit()
    elif ns.config == "multicore":
        bench_multicore()
    else:
        bench_rangestress()
    if ns.guard:
        raise SystemExit(run_guard(LAST_RESULT) if LAST_RESULT else 0)


if __name__ == "__main__":
    main()
