"""Benchmark: conflict-graph edges resolved per second on the device tier.

Workload (BASELINE.md): synthetic Zipfian key contention — a window of
transactions over a Zipf(0.99) key universe with a deep per-key conflict
history, the shape of the reference's hot loop (CommandsForKey.mapReduceActive,
reference accord/local/CommandsForKey.java:614-650, invoked per key per
PreAccept).  The device resolves the whole window in one fused step: deps
masks + in-window conflict graph + MXU execution wavefront.

vs_baseline = speedup over the scalar host path on this machine (edges/s),
the stand-in for the reference's one-txn-at-a-time scan (the Java repo
publishes no numbers — BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import time

import numpy as np


def build_world(n_keys=1024, n_existing=65536, n_batch=512, seed=42,
                zipf_alpha=0.99):
    from accord_tpu.local.cfk import CommandsForKey, InternalStatus
    from accord_tpu.primitives.keys import Key
    from accord_tpu.primitives.timestamp import Domain, TxnId, TxnKind
    from accord_tpu.utils.random_source import RandomSource

    rng = RandomSource(seed)
    keys = [Key(i) for i in range(n_keys)]
    cfks = {k: CommandsForKey(k) for k in keys}
    kinds = [TxnKind.READ, TxnKind.WRITE]
    statuses = [InternalStatus.PREACCEPTED, InternalStatus.ACCEPTED,
                InternalStatus.COMMITTED, InternalStatus.STABLE,
                InternalStatus.APPLIED]

    # bounded-Zipf key picker (same scheme as the burn harness)
    weights = 1.0 / np.arange(1, n_keys + 1) ** zipf_alpha
    cdf = np.cumsum(weights / weights.sum())

    def pick_key():
        return keys[int(np.searchsorted(cdf, rng.next_float()))]

    hlc = 1000
    for _ in range(n_existing):
        hlc += 1 + rng.next_int(2)
        tid = TxnId.create(1, hlc, rng.pick(kinds), Domain.KEY,
                           rng.next_int(8))
        for k in {pick_key() for _ in range(1 + rng.next_int(3))}:
            cfks[k].update(tid, rng.pick(statuses), None)
    batch = []
    for _ in range(n_batch):
        hlc += 1 + rng.next_int(2)
        tid = TxnId.create(1, hlc, rng.pick(kinds), Domain.KEY,
                           rng.next_int(8))
        batch.append((tid, sorted({pick_key() for _ in range(1 + rng.next_int(4))})))
    return list(cfks.values()), batch


def scalar_edges_per_sec(cfks, batch):
    by_key = {c.key: c for c in cfks}
    edges = 0

    def count(_):
        nonlocal edges
        edges += 1

    t0 = time.perf_counter()
    for tid, keyset in batch:
        for k in keyset:
            by_key[k].map_reduce_active(tid, tid.kind.witnesses(), count)
    dt = time.perf_counter() - t0
    return edges / dt, edges


def main():
    import jax

    from accord_tpu.ops.encode import BatchEncoder
    from accord_tpu.ops.sharded import resolve_step

    cfks, batch = build_world()
    enc = BatchEncoder(cfks, batch)
    s, b = enc.state, enc.dbatch
    args = [jax.device_put(x) for x in
            (s.entry_rank, s.entry_eat_rank, s.entry_key, s.entry_status,
             s.entry_kind, b.txn_rank, b.txn_witness_mask, b.txn_kind,
             b.touches)]

    # compile + warm up
    out = resolve_step(*args)
    jax.block_until_ready(out)
    edges = int(np.asarray(out[1]).sum())

    iters = 30
    t0 = time.perf_counter()
    for _ in range(iters):
        out = resolve_step(*args)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    device_eps = edges * iters / dt

    scalar_eps, scalar_edges = scalar_edges_per_sec(cfks, batch)
    assert scalar_edges == edges, (
        f"device/scalar edge mismatch: {edges} vs {scalar_edges}")

    print(json.dumps({
        "metric": "conflict_graph_edges_resolved_per_sec",
        "value": round(device_eps, 1),
        "unit": "edges/s",
        "vs_baseline": round(device_eps / scalar_eps, 2),
    }))


if __name__ == "__main__":
    main()
