"""Triage seed 16005: CommitInvalidate arriving at a STABLE command.

Taps every protocol transition and coordinator decision touching the suspect
txn, then replays the failing burn.
"""
import sys

SUSPECT = "W[7,61143672,2]"
SUSPECT2 = "W[7,70226780,3]"


def tap(node_or_store, what, **fields):
    import accord_tpu.sim.burn as B
    t = CLUSTER[0].queue.clock.now_us / 1e6 if CLUSTER[0] else -1
    print(f"{t:10.3f} {node_or_store} {what} "
          + " ".join(f"{k}={v}" for k, v in fields.items()), flush=True)


CLUSTER = [None]


def main():
    from accord_tpu.local import commands as C
    from accord_tpu.coordinate import recover as R
    from accord_tpu.coordinate import invalidate as I
    from accord_tpu.sim.burn import BurnRun

    def match(txn_id):
        return repr(txn_id) in (SUSPECT, SUSPECT2)

    def describe_deps(args, kw):
        from accord_tpu.primitives.deps import Deps
        out = []
        for v in list(args) + list(kw.values()):
            if isinstance(v, Deps):
                ids = [repr(t) for t in v.sorted_txn_ids()]
                out.append({"has_W": SUSPECT in ids,
                            "n": len(ids),
                            "ids": [i for i in ids if "[7," in i][:12]})
        return out

    # ---- command-store transitions ----
    for name in ("preaccept", "recover", "accept", "accept_invalidate",
                 "preaccept_invalidate", "commit", "precommit",
                 "commit_invalidate", "apply"):
        orig = getattr(C, name)

        def wrap(orig=orig, name=name):
            def inner(safe_store, txn_id, *a, **kw):
                if match(txn_id):
                    cmd = safe_store.store.commands.get(txn_id)
                    before = cmd.save_status.name if cmd else "NONE"
                    out = orig(safe_store, txn_id, *a, **kw)
                    cmd = safe_store.store.commands.get(txn_id)
                    after = cmd.save_status.name if cmd else "NONE"
                    extra = {}
                    if cmd is not None:
                        extra = dict(prom=cmd.promised, acc=cmd.accepted_ballot,
                                     at=cmd.execute_at)
                    deps_info = describe_deps(a, kw)
                    if deps_info:
                        extra["deps"] = deps_info
                    tap(f"n{safe_store.store.node.id}st{safe_store.store.id}",
                        f"{name}({txn_id!r})", before=before, after=after,
                        out=(out if not isinstance(out, tuple) else out[0]),
                        **extra)
                    return out
                return orig(safe_store, txn_id, *a, **kw)
            return inner
        setattr(C, name, wrap())

    # re-bind names imported into message modules
    import accord_tpu.messages.preaccept as MP
    import accord_tpu.messages.accept as MA
    import accord_tpu.messages.commit as MC
    import accord_tpu.messages.apply_msg as MAp
    import accord_tpu.messages.recover as MR
    for mod in (MP, MA, MC, MAp, MR):
        mod.C = C

    # ---- recovery coordinator decisions ----
    orig_recover = R.Recover._recover
    def rec(self):
        if match(self.txn_id):
            oks = {f: (ok.status.name, str(ok.accepted_ballot),
                       str(ok.execute_at), ok.rejects_fast_path,
                       str(ok.earlier_no_witness.sorted_txn_ids()
                           if not ok.earlier_no_witness.is_empty else []))
                   for f, ok in self.oks.items()}
            tap(f"n{self.node.id}", "Recover._recover", ballot=self.ballot,
                oks=oks, tracker_rejects=self.tracker.rejects_fast_path())
        return orig_recover(self)
    R.Recover._recover = rec

    for meth in ("_invalidate", "_commit_invalidate", "_propose", "_execute",
                 "_persist_outcome", "_retry", "_await_commits", "_fail",
                 "_succeed"):
        orig = getattr(R.Recover, meth)

        def wrapm(orig=orig, meth=meth):
            def inner(self, *a, **kw):
                if match(self.txn_id):
                    tap(f"n{self.node.id}", f"Recover{meth}",
                        ballot=self.ballot, done=self.done,
                        arg=(repr(a[0])[:120] if a else ""))
                return orig(self, *a, **kw)
            return inner
        setattr(R.Recover, meth, wrapm())

    # ---- name the fast-path-reject evidence ----
    from accord_tpu.local.store import SafeCommandStore as SCS
    orig_rfp = SCS.rejects_fast_path

    def rfp(self, txn_id, participants):
        out = orig_rfp(self, txn_id, participants)
        if match(txn_id) and out:
            detail = {}
            for cfk in self._participant_cfks(participants):
                sa = cfk.started_after_without_witnessing_ids(txn_id)
                ea = cfk.executes_after_without_witnessing_ids(txn_id)
                if sa or ea:
                    detail[repr(cfk.key)] = {
                        "started_after_no_witness": [repr(t) for t in sa],
                        "executes_after_no_witness": [repr(t) for t in ea]}
            tap(f"n{self.store.node.id}st{self.store.id}",
                "rejects_fast_path=True", detail=detail)
        return out
    SCS.rejects_fast_path = rfp

    orig_ci = I.commit_invalidate
    def ci(node, txn_id, route):
        if match(txn_id):
            tap(f"n{node.id}", "coordinate.commit_invalidate(fanout)")
        return orig_ci(node, txn_id, route)
    I.commit_invalidate = ci
    R.commit_invalidate = ci

    for meth in ("start", "_promised", "_fail"):
        if hasattr(I.ProposeInvalidate, meth):
            orig = getattr(I.ProposeInvalidate, meth)

            def wrapp(orig=orig, meth=meth):
                def inner(self, *a, **kw):
                    if match(self.txn_id):
                        tap(f"n{self.node.id}", f"ProposeInvalidate{meth}",
                            ballot=getattr(self, 'ballot', None))
                    return orig(self, *a, **kw)
                return inner
            setattr(I.ProposeInvalidate, meth, wrapp())

    run = BurnRun(16005, 400, nodes=3, keys=12, n_shards=2, drop_prob=0.22,
                  partitions=True, clock_drift=True, num_command_stores=4,
                  store_factory=None)
    # delayed stores like the CLI
    from accord_tpu.sim.delayed_store import DelayedCommandStore
    from accord_tpu.utils.random_source import RandomSource
    run = BurnRun(16005, 400, nodes=3, keys=12, n_shards=2, drop_prob=0.22,
                  partitions=True, clock_drift=True, num_command_stores=4,
                  store_factory=DelayedCommandStore.factory(
                      RandomSource(16005 ^ 0x5D5D)))
    CLUSTER[0] = run.cluster
    try:
        run.run()
        print("UNEXPECTED: run passed")
    except Exception as e:
        print(f"FAILED as expected: {e}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
